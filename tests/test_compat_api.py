"""1.x-compat aliases and auxiliary modules (reference: the DEFINE_ALIAS
block of python/paddle/__init__.py)."""
import os
import numpy as np
import pytest

import paddle_tpu as paddle


def test_elementwise_and_reduce_aliases():
    x = paddle.to_tensor(np.array([[1., 2.], [3., 4.]], "float32"))
    y = paddle.to_tensor(np.array([[1., 1.], [1., 1.]], "float32"))
    np.testing.assert_allclose(paddle.elementwise_add(x, y).numpy(),
                               [[2, 3], [4, 5]])
    np.testing.assert_allclose(paddle.elementwise_div(x, y).numpy(),
                               x.numpy())
    assert float(paddle.reduce_mean(x).numpy()) == 2.5
    np.testing.assert_allclose(
        paddle.reduce_max(x, dim=0).numpy(), [3, 4])


def test_slice_ops():
    x = paddle.to_tensor(np.arange(24).reshape(2, 3, 4).astype("float32"))
    s = paddle.slice(x, axes=[1, 2], starts=[0, 1], ends=[2, 3])
    assert s.shape == [2, 2, 2]
    ss = paddle.strided_slice(x, axes=[2], starts=[0], ends=[4],
                              strides=[2])
    assert ss.shape == [2, 3, 2]
    c = paddle.crop_tensor(x, shape=[1, 2, 2], offsets=[0, 1, 1])
    assert c.shape == [1, 2, 2]
    parts = paddle.unstack(x, axis=0)
    assert len(parts) == 2 and parts[0].shape == [3, 4]


def test_creation_compat():
    t = paddle.fill_constant([2, 2], "float32", 3.0)
    np.testing.assert_allclose(t.numpy(), np.full((2, 2), 3.0))
    g = paddle.create_global_var([3], 1.5, "float32", persistable=True)
    assert g.persistable
    p = paddle.create_parameter([4, 4], "float32")
    assert p.trainable


def test_nan_inf_checks():
    x = paddle.to_tensor(np.array([1.0, np.inf], "float32"))
    assert bool(paddle.has_inf(x).numpy())
    assert not bool(paddle.has_nan(x).numpy())


def test_inplace_variants():
    x = paddle.to_tensor(np.array([4.0], "float32"))
    y = paddle.sqrt_(x)
    assert y is x
    assert float(x.numpy()) == 2.0


def test_regularizer_weight_decay():
    from paddle_tpu import optimizer, regularizer, nn
    net = nn.Linear(2, 2)
    opt = optimizer.Momentum(learning_rate=0.1,
                             weight_decay=regularizer.L2Decay(1e-4),
                             parameters=net.parameters())
    assert opt._weight_decay == pytest.approx(1e-4)


def test_batch_reader():
    def reader():
        for i in range(7):
            yield i
    b = paddle.batch(reader, 3)
    batches = list(b())
    assert batches == [[0, 1, 2], [3, 4, 5], [6]]
    b2 = paddle.batch(reader, 3, drop_last=True)
    assert list(b2()) == [[0, 1, 2], [3, 4, 5]]


def test_dygraph_mode_toggles():
    assert paddle.in_dygraph_mode()
    paddle.disable_dygraph()
    assert not paddle.in_dygraph_mode()
    paddle.enable_dygraph()
    assert paddle.in_dygraph_mode()


def test_summary_and_flops():
    from paddle_tpu import nn
    net = nn.Sequential(nn.Linear(8, 4), nn.ReLU())
    info = paddle.summary(net)
    assert info["total_params"] == 8 * 4 + 4
    assert paddle.flops(net, None) == 2 * 8 * 4


def test_auto_checkpoint_roundtrip(tmp_path, monkeypatch):
    from paddle_tpu.incubate.checkpoint import TrainEpochRange
    from paddle_tpu import nn, optimizer
    monkeypatch.setenv("PADDLE_RUNNING_ENV", "PADDLE_EDL_AUTO_CHECKPOINT")
    monkeypatch.setenv("PADDLE_CHECKPOINT_DIR", str(tmp_path))

    paddle.seed(0)
    net = nn.Linear(4, 2)
    opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    r = TrainEpochRange(5, name="job1").attach(net, opt)
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    done = []
    w_after_epoch1 = None
    for epoch in r.get():
        loss = paddle.mean(net(x) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        done.append(epoch)
        if epoch == 1:
            w_after_epoch1 = dict(
                net.named_parameters())["weight"].numpy().copy()
        if epoch == 2:
            break  # crash mid-epoch-2: its snapshot never happens
    # epochs 0..1 snapshotted (break skips epoch 2's save)
    assert done == [0, 1, 2]

    # relaunch: a fresh layer resumes from the last snapshot (epoch 1)
    paddle.seed(0)
    net2 = nn.Linear(4, 2)
    opt2 = optimizer.SGD(learning_rate=0.1, parameters=net2.parameters())
    r2 = TrainEpochRange(5, name="job1").attach(net2, opt2)
    epochs2 = list(r2.get().__iter__().__next__() for _ in range(1))
    assert epochs2[0] == 2  # resumes at epoch 2
    np.testing.assert_allclose(
        dict(net2.named_parameters())["weight"].numpy(), w_after_epoch1,
        rtol=1e-6)


def test_misc_shims():
    assert paddle.get_cudnn_version() is None
    assert paddle.VarBase is paddle.Tensor
    assert isinstance(paddle.compat.to_text(b"abc"), str)
    x = paddle.to_tensor([1.0])
    assert paddle.get_tensor_from_selected_rows(x) is x
    assert paddle.__version__.startswith("2.")


def test_profiler_chrome_trace_export(tmp_path, capsys):
    from paddle_tpu.utils import profiler as prof
    import json as _json
    path = str(tmp_path / "trace.json")
    prof.start_profiler(log_dir=str(tmp_path / "xplane"))
    with prof.RecordEvent("step"):
        paddle.to_tensor([1.0]) + 1.0
    with prof.RecordEvent("step"):
        pass
    events = prof.stop_profiler(profile_path=path)
    assert len(events) == 2
    trace = _json.load(open(path))
    assert len(trace["traceEvents"]) == 2
    assert trace["traceEvents"][0]["name"] == "step"
    out = capsys.readouterr().out
    assert "step" in out and "Calls" in out


def test_profiler_sorted_key_max_uses_event_durations(tmp_path, capsys,
                                                      monkeypatch):
    """sorted_key='max'/'min' must sort by the per-event extreme
    DURATION, not total time (review satellite): 'a' has the larger
    total (3x8), 'b' the larger single event (1x20)."""
    from paddle_tpu.utils import profiler as prof
    ticks = iter([0.0, 8.0, 10.0, 18.0, 20.0, 28.0, 30.0, 50.0])
    monkeypatch.setattr(prof.time, "perf_counter", lambda: next(ticks))
    prof.start_profiler(log_dir=str(tmp_path / "xplane"))
    for name in ("a", "a", "a", "b"):
        with prof.RecordEvent(name):
            pass
    prof.stop_profiler(sorted_key="max")
    rows = [l.split()[0] for l in capsys.readouterr().out.splitlines()
            if l and l.split()[0] in ("a", "b")]
    assert rows == ["b", "a"]  # max(b)=20 > max(a)=8 despite total a=24


def test_profiler_sorted_key_min_descends(tmp_path, capsys, monkeypatch):
    """'min' sorts by per-event MIN duration, descending like every
    other key (reference EventSortingKey::kMin)."""
    from paddle_tpu.utils import profiler as prof
    ticks = iter([0.0, 8.0, 10.0, 18.0, 20.0, 28.0, 30.0, 50.0])
    monkeypatch.setattr(prof.time, "perf_counter", lambda: next(ticks))
    prof.start_profiler(log_dir=str(tmp_path / "xplane"))
    for name in ("a", "a", "a", "b"):
        with prof.RecordEvent(name):
            pass
    prof.stop_profiler(sorted_key="min")
    rows = [l.split()[0] for l in capsys.readouterr().out.splitlines()
            if l and l.split()[0] in ("a", "b")]
    assert rows == ["b", "a"]  # min(b)=20 > min(a)=8


def test_reset_profiler_thread_safe_against_exits(tmp_path):
    """reset_profiler takes the event-list lock; hammer it against
    concurrent RecordEvent exits and require no lost-update crash."""
    import threading
    from paddle_tpu.utils import profiler as prof
    prof.start_profiler(log_dir=str(tmp_path / "xplane"))
    stop = threading.Event()

    def record():
        while not stop.is_set():
            with prof.RecordEvent("spin"):
                pass

    t = threading.Thread(target=record)
    t.start()
    try:
        for _ in range(200):
            prof.reset_profiler()
    finally:
        stop.set()
        t.join()
        prof.stop_profiler()


def test_launch_reserves_master_port_until_spawn():
    """ADVICE low (launch.py): the probe socket is HELD until workers
    start, so a concurrent launch cannot steal the master port between
    probe and bind; SO_REUSEADDR lets the real owner bind the instant
    the probe closes."""
    import socket
    from paddle_tpu.distributed.launch import _free_port, _reserve_port
    s = _reserve_port()
    port = s.getsockname()[1]
    probe = socket.socket()
    try:
        with pytest.raises(OSError):  # held: nobody can take it
            probe.bind(("127.0.0.1", port))
    finally:
        probe.close()
    s.close()
    owner = socket.socket()  # released: owner binds immediately
    owner.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    try:
        owner.bind(("127.0.0.1", port))
    finally:
        owner.close()
    assert isinstance(_free_port(), int)  # legacy helper still works


def test_dlpack_roundtrip():
    from paddle_tpu.utils import dlpack
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    cap = dlpack.to_dlpack(x)
    y = dlpack.from_dlpack(cap)
    np.testing.assert_array_equal(y.numpy(), x.numpy())


def test_local_fs_operations(tmp_path):
    from paddle_tpu.distributed.fleet.utils import LocalFS
    fs = LocalFS()
    d = str(tmp_path / "dir")
    fs.mkdirs(d)
    assert fs.is_dir(d)
    f = str(tmp_path / "dir" / "a.txt")
    fs.touch(f)
    assert fs.is_file(f)
    dirs, files = fs.ls_dir(str(tmp_path / "dir"))
    assert files == ["a.txt"]
    fs.mv(f, str(tmp_path / "dir" / "b.txt"))
    assert fs.is_exist(str(tmp_path / "dir" / "b.txt"))
    fs.delete(d)
    assert not fs.is_exist(d)


def test_data_feeder():
    from paddle_tpu.io import DataFeeder

    class V:
        name = "x"

    feeder = DataFeeder(feed_list=[V(), "y"])
    batch = feeder.feed([(np.ones(3), 0), (np.zeros(3), 1)])
    assert batch["x"].shape == (2, 3)
    assert list(batch["y"]) == [0, 1]


def test_reduce_lr_on_plateau_callback():
    from paddle_tpu import nn, optimizer
    from paddle_tpu.hapi.callbacks import ReduceLROnPlateau

    net = nn.Linear(2, 1)
    opt = optimizer.SGD(learning_rate=1.0, parameters=net.parameters())

    class FakeModel:
        _optimizer = opt

    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                           verbose=0)
    cb.model = FakeModel()
    cb.on_epoch_end(0, {"loss": 1.0})
    cb.on_epoch_end(1, {"loss": 1.0})   # wait 1
    cb.on_epoch_end(2, {"loss": 1.0})   # wait 2 -> reduce
    assert opt.get_lr() == pytest.approx(0.5)
    cb.on_epoch_end(3, {"loss": 0.2})   # improvement resets
    cb.on_epoch_end(4, {"loss": 0.2})
    assert opt.get_lr() == pytest.approx(0.5)


def test_visualdl_callback(tmp_path):
    import json
    from paddle_tpu.hapi.callbacks import VisualDL
    cb = VisualDL(log_dir=str(tmp_path))
    cb.on_train_begin()
    for i in range(10):
        cb.on_train_batch_end(i, {"loss": 1.0 - i * 0.01})
    cb.on_epoch_end(0, {"loss": 0.9, "acc": 0.5})
    cb.on_train_end()
    lines = [json.loads(l) for l in
             open(tmp_path / "scalars.jsonl").read().splitlines()]
    assert any(r["tag"] == "train" for r in lines)
    assert any(r["tag"] == "epoch" and r["acc"] == 0.5 for r in lines)


def test_multivariate_normal_diag():
    import math
    from paddle_tpu.distribution import MultivariateNormalDiag
    loc = paddle.to_tensor(np.zeros(3, "float32"))
    scale = paddle.to_tensor(np.diag([1.0, 2.0, 0.5]).astype("float32"))
    d = MultivariateNormalDiag(loc, scale)
    s = d.sample([100])
    assert s.shape == [100, 3]
    lp = float(d.log_prob(paddle.to_tensor(np.zeros(3, "float32"))).numpy())
    expect = -0.5 * 3 * math.log(2 * math.pi) - math.log(1 * 2 * 0.5)
    assert lp == pytest.approx(expect, rel=1e-5)
    ent = float(d.entropy().numpy())
    assert ent == pytest.approx(
        0.5 * 3 * (1 + math.log(2 * math.pi)) + math.log(1.0),
        rel=1e-5)


def test_traced_layer(tmp_path):
    from paddle_tpu import nn
    paddle.seed(0)
    net = nn.Linear(4, 2)
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    outs, traced = paddle.jit.TracedLayer.trace(net, [x])
    np.testing.assert_allclose(outs[0].numpy(), net(x).numpy(),
                               rtol=1e-5)
    traced.save_inference_model(str(tmp_path / "traced"))
    loaded = paddle.jit.load(str(tmp_path / "traced"))
    np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                               rtol=1e-5)


def test_learning_rate_decay_alias():
    from paddle_tpu.optimizer.lr import LearningRateDecay, LRScheduler
    assert LearningRateDecay is LRScheduler


def test_reduce_lr_cooldown_pauses_patience():
    from paddle_tpu import nn, optimizer
    from paddle_tpu.hapi.callbacks import ReduceLROnPlateau
    net = nn.Linear(2, 1)
    opt = optimizer.SGD(learning_rate=1.0, parameters=net.parameters())

    class FakeModel:
        _optimizer = opt

    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                           cooldown=3, verbose=0)
    cb.model = FakeModel()
    for epoch in range(5):   # constant loss, never improves
        cb.on_epoch_end(epoch, {"loss": 1.0})
    # epoch0 sets best; epoch1 reduces (patience 1); epochs 2-4 drain the
    # 3-epoch cooldown with NO further reduction
    assert opt.get_lr() == pytest.approx(0.5)
    cb.on_epoch_end(5, {"loss": 1.0})    # cooldown over: reduces again
    assert opt.get_lr() == pytest.approx(0.25)


def test_fluid_dygraph_one_x_exports():
    import paddle_tpu.fluid as fluid
    assert hasattr(fluid.dygraph, "TracedLayer")
    assert hasattr(fluid.dygraph, "LearningRateDecay")
