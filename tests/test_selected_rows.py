"""Sparse (SelectedRows) embedding gradients on the eager tape.

Reference parity: nn.Embedding(sparse=True) -> lookup_table_v2 emitting
SelectedRows (framework/selected_rows.h, imperative/gradient_accumulator.cc
SelectedRows path) consumed by sparse optimizer kernels
(operators/optimizers/adam_op.h SparseAdamFunctor, sgd_op.h, momentum_op.h).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.core.selected_rows import SelectedRows

VOCAB, DIM = 64, 8


def _make_pair(seed=0, sparse=True, vocab=VOCAB, dim=DIM):
    """Two identical embeddings, one sparse one dense."""
    paddle.seed(seed)
    emb_s = nn.Embedding(vocab, dim, sparse=sparse)
    emb_d = nn.Embedding(vocab, dim)
    emb_d.weight.set_value(emb_s.weight.numpy())
    return emb_s, emb_d


def _ids(shape=(4, 6), seed=0, vocab=VOCAB):
    rng = np.random.RandomState(seed)
    return paddle.to_tensor(rng.randint(0, vocab, shape).astype(np.int64))


class TestSparseGradRepresentation:
    def test_backward_produces_selected_rows(self):
        emb, _ = _make_pair()
        x = _ids()
        loss = emb(x).sum()
        loss.backward()
        g = emb.weight.grad
        assert isinstance(g, SelectedRows)
        # O(batch*seq) values, not O(vocab)
        assert list(g.values.shape) == [4 * 6, DIM]
        assert g.height == VOCAB
        assert not g.is_densified()

    def test_matches_dense_gradient(self):
        emb_s, emb_d = _make_pair()
        x = _ids()
        (emb_s(x) ** 2).sum().backward()
        (emb_d(x) ** 2).sum().backward()
        np.testing.assert_allclose(emb_s.weight.grad.numpy(),
                                   emb_d.weight.grad.numpy(), rtol=1e-6)

    def test_padding_idx_rows_zero(self):
        paddle.seed(0)
        emb = nn.Embedding(VOCAB, DIM, padding_idx=3, sparse=True)
        x = paddle.to_tensor(np.array([[1, 3, 5, 3]], np.int64))
        emb(x).sum().backward()
        g = emb.weight.grad
        assert isinstance(g, SelectedRows)
        dense = g.numpy()
        np.testing.assert_array_equal(dense[3], np.zeros(DIM))
        assert np.abs(dense[1]).sum() > 0

    def test_accumulation_stays_sparse(self):
        emb, emb_d = _make_pair()
        x1, x2 = _ids(seed=1), _ids(seed=2)
        emb(x1).sum().backward()
        emb(x2).sum().backward()
        g = emb.weight.grad
        assert isinstance(g, SelectedRows)
        assert list(g.values.shape) == [2 * 4 * 6, DIM]
        emb_d(x1).sum().backward()
        emb_d(x2).sum().backward()
        np.testing.assert_allclose(g.numpy(), emb_d.weight.grad.numpy(),
                                   rtol=1e-6)

    def test_merged_dedups(self):
        g = SelectedRows(np.array([2, 5, 2]),
                         np.array([[1.0], [2.0], [3.0]], np.float32), 10)
        rows, vals = g.merged()
        np.testing.assert_array_equal(np.asarray(rows), [2, 5])
        np.testing.assert_allclose(np.asarray(vals), [[4.0], [2.0]])

    def test_jit_path_unaffected(self):
        # under the functional/jit path sparse=True must fall back to the
        # dense primitive (XLA fuses the scatter-add)
        import jax
        import jax.numpy as jnp
        from paddle_tpu.nn import functional as F

        w = jnp.ones((VOCAB, DIM), jnp.float32)
        ids = jnp.zeros((2, 3), jnp.int32)

        def f(w):
            from paddle_tpu.core.tensor import Tensor
            t = F.embedding(Tensor(ids), Tensor(w, stop_gradient=True),
                            sparse=True)
            return t._data.sum()

        out = jax.jit(jax.grad(f))(w)
        assert out.shape == (VOCAB, DIM)


class TestSparseOptimizers:
    @pytest.mark.parametrize("make_opt", [
        lambda ps: optimizer.SGD(learning_rate=0.1, parameters=ps),
        lambda ps: optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                      parameters=ps),
        lambda ps: optimizer.Adam(learning_rate=0.05, parameters=ps),
        lambda ps: optimizer.AdamW(learning_rate=0.05, weight_decay=0.01,
                                   parameters=ps),
        # no sparse override -> base densifying fallback
        lambda ps: optimizer.RMSProp(learning_rate=0.05, parameters=ps),
    ], ids=["sgd", "momentum", "adam", "adamw", "rmsprop-fallback"])
    def test_matches_dense_update(self, make_opt):
        emb_s, emb_d = _make_pair()
        opt_s = make_opt([emb_s.weight])
        opt_d = make_opt([emb_d.weight])
        for step in range(3):
            x = _ids(seed=step)
            (emb_s(x) ** 2).sum().backward()
            (emb_d(x) ** 2).sum().backward()
            opt_s.step()
            opt_d.step()
            opt_s.clear_grad()
            opt_d.clear_grad()
        np.testing.assert_allclose(emb_s.weight.numpy(),
                                   emb_d.weight.numpy(), rtol=2e-5,
                                   atol=1e-6)

    def test_lazy_adam_touches_only_rows(self):
        paddle.seed(0)
        emb = nn.Embedding(VOCAB, DIM, sparse=True)
        w0 = emb.weight.numpy().copy()
        opt = optimizer.Adam(learning_rate=0.1, parameters=[emb.weight],
                             lazy_mode=True)
        x = paddle.to_tensor(np.array([[1, 2, 3]], np.int64))
        emb(x).sum().backward()
        opt.step()
        w1 = emb.weight.numpy()
        touched = {1, 2, 3}
        for r in range(VOCAB):
            if r in touched:
                assert np.abs(w1[r] - w0[r]).max() > 0
            else:
                np.testing.assert_array_equal(w1[r], w0[r])
        # untouched moments stay zero
        state = opt._accumulators[id(emb.weight)]
        m1 = np.asarray(state["moment1"])
        assert np.abs(m1[[r for r in range(VOCAB)
                          if r not in touched]]).max() == 0

    def test_never_densified_through_full_step(self):
        """The memory claim: grad -> clip -> optimizer applies without ever
        materializing the [vocab, dim] dense gradient."""
        emb, _ = _make_pair()
        opt = optimizer.Adam(
            learning_rate=0.1, parameters=[emb.weight], lazy_mode=True,
            grad_clip=nn.ClipGradByGlobalNorm(1.0))
        emb(_ids()).sum().backward()
        g = emb.weight.grad
        opt.step()
        opt.clear_grad()
        assert isinstance(g, SelectedRows) and not g.is_densified()

    def test_clip_matches_dense(self):
        emb_s, emb_d = _make_pair()
        clip_s = nn.ClipGradByGlobalNorm(0.01)
        clip_d = nn.ClipGradByGlobalNorm(0.01)
        opt_s = optimizer.SGD(learning_rate=1.0, parameters=[emb_s.weight],
                              grad_clip=clip_s)
        opt_d = optimizer.SGD(learning_rate=1.0, parameters=[emb_d.weight],
                              grad_clip=clip_d)
        x = _ids()
        (emb_s(x) ** 2).sum().backward()
        (emb_d(x) ** 2).sum().backward()
        opt_s.step()
        opt_d.step()
        np.testing.assert_allclose(emb_s.weight.numpy(),
                                   emb_d.weight.numpy(), rtol=1e-5)


class TestDenseMutation:
    def test_data_setter_resyncs_sparse_view(self):
        """In-place dense mutation (GradScaler.unscale_, clip_grad_norm_
        write g._data) must be visible to sparse consumers — a stale
        merged() would apply pre-mutation values."""
        g = SelectedRows(np.array([1, 1, 3]),
                         np.array([[1.0], [2.0], [4.0]], np.float32), 5)
        g._data = g._data * 0.5
        rows, vals = g.merged()
        dense = np.zeros((5, 1), np.float32)
        for r, v in zip(rows, np.asarray(vals)):
            dense[int(r)] = v
        np.testing.assert_allclose(dense[1], [1.5])
        np.testing.assert_allclose(dense[3], [2.0])

    def test_grad_scaler_unscale_applies_to_sparse_step(self):
        from paddle_tpu import amp
        emb_s, emb_d = _make_pair()
        opt_s = optimizer.SGD(learning_rate=0.1,
                              parameters=[emb_s.weight])
        opt_d = optimizer.SGD(learning_rate=0.1,
                              parameters=[emb_d.weight])
        scaler = amp.GradScaler(init_loss_scaling=1024.0)
        x = _ids()
        scaler.scale((emb_s(x) ** 2).sum()).backward()
        scaler.step(opt_s)
        scaler.update()
        (emb_d(x) ** 2).sum().backward()
        opt_d.step()
        np.testing.assert_allclose(emb_s.weight.numpy(),
                                   emb_d.weight.numpy(), rtol=1e-5)

    def test_clip_grad_norm_applies_to_sparse_step(self):
        emb, _ = _make_pair()
        opt = optimizer.SGD(learning_rate=1.0, parameters=[emb.weight])
        (emb(_ids()) ** 2).sum().backward()
        w0 = emb.weight.numpy().copy()
        nn.utils.clip_grad_norm_([emb.weight], max_norm=1e-4)
        opt.step()
        # with the tiny clip the update must be tiny too
        assert np.abs(emb.weight.numpy() - w0).max() < 1e-3


class TestCompatShims:
    def test_get_tensor_from_selected_rows(self):
        g = SelectedRows(np.array([0, 2]),
                         np.array([[1.0, 1.0], [2.0, 2.0]], np.float32), 4)
        t = paddle.get_tensor_from_selected_rows(g)
        assert not isinstance(t, SelectedRows)
        assert t.shape == [4, 2]
        np.testing.assert_allclose(t.numpy()[2], [2.0, 2.0])

    def test_merge_selected_rows_legacy(self):
        from paddle_tpu.nn.functional import legacy
        g = SelectedRows(np.array([1, 1]),
                         np.array([[1.0], [2.0]], np.float32), 4)
        m = legacy.merge_selected_rows(g)
        assert isinstance(m, SelectedRows)
        np.testing.assert_array_equal(np.asarray(m.rows), [1])
        np.testing.assert_allclose(np.asarray(m.values), [[3.0]])


class TestDoubleGrad:
    def test_create_graph_falls_back_dense(self):
        paddle.seed(0)
        emb = nn.Embedding(8, 4, sparse=True)
        x = paddle.to_tensor(np.array([[1, 2]], np.int64))
        out = emb(x)
        loss = (out ** 2).sum()
        (g,) = paddle.grad([loss], [emb.weight], create_graph=True)
        # second order: d/dw of sum(g*g) = ... runs through dense primal
        gg = (g ** 2).sum()
        gg.backward()
        assert emb.weight.grad is not None
