"""dygraph→static AST control-flow conversion.

Reference parity: unittests/dygraph_to_static/ — run the same nn.Layer
eagerly and via @to_static, asserting numerical equality (the reference's
72-file equivalence suite pattern), now including tensor-dependent
if/while that the round-1 trace-only to_static rejected.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit import to_static
from paddle_tpu.jit.dy2static import (convert_function, convert_ifelse,
                                      convert_while_loop,
                                      UnsupportedControlFlow)


class BranchNet(nn.Layer):
    """Tensor-dependent if/else (reference: test_ifelse.py patterns)."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 4)

    def forward(self, x):
        h = self.fc(x)
        if paddle.mean(h) > 0:
            y = h * 2.0
        else:
            y = h - 1.0
        return paddle.sum(y)


class LoopNet(nn.Layer):
    """Tensor-dependent while (reference: test_loop.py patterns)."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(3, 3)

    def forward(self, x):
        h = self.fc(x)
        i = paddle.to_tensor(np.zeros((), np.float32))
        s = paddle.zeros([3], "float32")
        while i < 4.0:
            s = s + paddle.mean(h, axis=0) * (i + 1.0)
            i = i + 1.0
        return paddle.sum(s)


class ReturnBranchNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 2)

    def forward(self, x):
        h = self.fc(x)
        if paddle.sum(h) > 0:
            return h * 3.0
        else:
            return h - 5.0


def _eager_vs_static(net_cls, x):
    paddle.seed(42)
    net = net_cls()
    eager = net.forward(paddle.to_tensor(x))
    static_net = to_static(net)
    static = static_net(paddle.to_tensor(x))
    e = np.asarray(eager.numpy())
    s = np.asarray(static.numpy())
    np.testing.assert_allclose(e, s, rtol=1e-5, atol=1e-6)
    return net, static_net


class TestDy2StaticEquivalence:
    def test_ifelse_true_branch(self):
        x = np.full((2, 4), 0.5, np.float32)
        _eager_vs_static(BranchNet, x)

    def test_ifelse_false_branch(self):
        x = np.full((2, 4), -0.5, np.float32)
        _eager_vs_static(BranchNet, x)

    def test_branches_actually_differ(self):
        paddle.seed(1)
        net = BranchNet()
        st = to_static(net)
        a = float(st(paddle.to_tensor(
            np.full((2, 4), 2.0, np.float32))).numpy())
        b = float(st(paddle.to_tensor(
            np.full((2, 4), -2.0, np.float32))).numpy())
        # same compiled program, both branch results reachable
        assert not np.isclose(a, b)

    def test_while_loop(self):
        x = np.random.RandomState(0).randn(2, 3).astype(np.float32)
        _eager_vs_static(LoopNet, x)

    def test_return_in_both_branches(self):
        x = np.full((2, 4), 1.0, np.float32)
        _eager_vs_static(ReturnBranchNet, x)
        x = np.full((2, 4), -1.0, np.float32)
        _eager_vs_static(ReturnBranchNet, x)

    def test_plain_function_conversion(self):
        @to_static
        def f(x):
            if paddle.sum(x) > 0:
                y = x * 10.0
            else:
                y = x / 10.0
            return paddle.mean(y)

        pos = f(paddle.to_tensor(np.ones((3,), np.float32)))
        neg = f(paddle.to_tensor(-np.ones((3,), np.float32)))
        np.testing.assert_allclose(float(pos.numpy()), 10.0, rtol=1e-5)
        np.testing.assert_allclose(float(neg.numpy()), -0.1, rtol=1e-5)

    def test_python_bool_control_flow_still_python(self):
        """Non-tensor predicates keep exact Python semantics."""

        def g(x, flag):
            if flag:
                y = x + 1
            else:
                y = x - 1
            return y

        conv = convert_function(g)
        assert conv is not None
        assert conv(5, True) == 6
        assert conv(5, False) == 4

    def test_bool_ops_on_tensors(self):
        def h(a, b):
            return convert_ifelse(
                paddle.to_tensor(True), lambda: a, lambda: b)

        def f(x):
            if (paddle.sum(x) > 0) and (paddle.max(x) < 10):
                y = x * 2.0
            else:
                y = x * 0.5
            return y

        conv = convert_function(f)
        assert conv is not None
        out = conv(paddle.to_tensor(np.ones((2,), np.float32)))
        np.testing.assert_allclose(out.numpy(), [2.0, 2.0], rtol=1e-6)

    def test_nothing_to_convert_returns_none(self):
        def f(x):
            return x + 1

        assert convert_function(f) is None

    def test_grad_flows_through_converted_branch(self):
        paddle.seed(3)
        net = BranchNet()
        st = to_static(net)
        x = paddle.to_tensor(np.full((2, 4), 1.5, np.float32))
        loss = st(x)
        loss.backward()
        grads = [p.grad for p in net.parameters()]
        assert any(g is not None and np.abs(g.numpy()).sum() > 0
                   for g in grads)

    def test_undefined_in_one_branch_raises_helpfully(self):
        def f(x):
            if paddle.sum(x) > 0:
                z = x * 2.0
            else:
                w = x * 3.0  # noqa: F841 — different name on purpose
            return x

        conv = convert_function(f)
        assert conv is not None
        import jax

        with pytest.raises(UnsupportedControlFlow, match="only one branch"):
            jax.jit(lambda a: conv(
                paddle.to_tensor(a))._data)(np.ones((2,), np.float32))

    def test_while_uninitialized_var_raises_helpfully(self):
        def cond(i):
            return i < 3

        def body(i):
            return (i + 1,)

        from paddle_tpu.jit.dy2static import _Undefined
        import jax

        with pytest.raises(UnsupportedControlFlow, match="initialize"):
            jax.jit(lambda a: convert_while_loop(
                lambda u: paddle.to_tensor(a).sum() > 0,
                lambda u: (u,), (_Undefined("tmp"),), ("tmp",)))(
                np.ones((2,), np.float32))


class TestReviewRegressions:
    def test_nested_return_keeps_python_semantics(self):
        """A return nested under for/with inside an if must NOT be moved
        into a closure (it would exit the closure, not the function)."""

        def f(x, flag):
            if flag:
                for i in range(2):
                    return x + i
            return x - 1

        conv = convert_function(f)
        # either unconverted (None) or converted with identical semantics
        g = conv or f
        assert g(10, True) == 10
        assert g(10, False) == 9

    def test_conditionally_bound_name_no_unbound_error(self):
        def f(x, items):
            if x > 0:
                total = 0
                for i in items:
                    total += i
                y = total
            else:
                y = -1
            return y

        conv = convert_function(f)
        assert conv is not None
        assert conv(1, []) == 0       # empty loop: i never binds
        assert conv(1, [5, 6]) == 11
        assert conv(-1, [5]) == -1

    def test_grad_flows_through_tensor_if_in_train_step(self):
        """convert_ifelse merges via the dispatched where op, so jax.grad
        through the compiled step sees the select (non-zero grads)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.jit.dy2static import convert_ifelse
        from paddle_tpu.core.tensor import Tensor

        def loss_fn(w):
            wt = Tensor(w, stop_gradient=True)
            pred = paddle.sum(wt) > 0
            out = convert_ifelse(pred, lambda: (wt * 2.0,),
                                 lambda: (wt * 3.0,))[0]
            return jnp.sum(out._data ** 2)

        w = np.full((3,), 2.0, np.float32)
        g = jax.grad(loss_fn)(w)
        np.testing.assert_allclose(np.asarray(g), 8.0 * w, rtol=1e-5)
        g2 = jax.grad(loss_fn)(-w)
        np.testing.assert_allclose(np.asarray(g2), 18.0 * -w, rtol=1e-5)

    def test_nested_tensor_if_inside_branch(self):
        """Generated __d2s_* helpers from a nested transform must not be
        threaded as branch variables (review finding)."""

        def f(x):
            if paddle.sum(x) > 0:
                if paddle.max(x) > 2.0:
                    y = x * 4.0
                else:
                    y = x * 2.0
            else:
                y = x * 0.5
            return y

        conv = convert_function(f)
        assert conv is not None
        import jax

        def run(a):
            return conv(paddle.to_tensor(a))._data

        out = jax.jit(run)(np.ones((2,), np.float32))
        np.testing.assert_allclose(np.asarray(out), [2.0, 2.0], rtol=1e-6)
        out = jax.jit(run)(np.full((2,), 3.0, np.float32))
        np.testing.assert_allclose(np.asarray(out), [12.0, 12.0],
                                   rtol=1e-6)
        out = jax.jit(run)(np.full((2,), -1.0, np.float32))
        np.testing.assert_allclose(np.asarray(out), [-0.5, -0.5],
                                   rtol=1e-6)

    def test_foreign_decorator_bails_to_trace(self):
        import functools

        def mydeco(fn):
            @functools.wraps(fn)
            def inner(*a, **k):
                return fn(*a, **k)
            return inner

        @mydeco
        def f(x):
            if paddle.sum(x) > 0:
                y = x * 2.0
            else:
                y = x * 3.0
            return y

        assert convert_function(f) is None

    def test_super_in_forward_bails_to_trace(self):
        class Base(nn.Layer):
            def forward(self, x):
                return x * 2.0

        class Child(Base):
            def forward(self, x):
                if paddle.sum(x) > 0:
                    y = super().forward(x)
                else:
                    y = x * 3.0
                return y

        # zero-arg super() => __class__ freevar => must NOT convert
        assert convert_function(Child.forward) is None
        # eager behavior intact
        c = Child()
        out = c(paddle.to_tensor(np.ones((2,), np.float32)))
        np.testing.assert_allclose(out.numpy(), [2.0, 2.0])

    def test_for_range_tensor_bound(self):
        """for i in range(tensor) converts (reference loop_transformer
        for->while) and produces identical accumulation."""

        def f(x, n):
            acc = paddle.zeros([2], "float32")
            for i in range(n):
                acc = acc + x * (float(1.0) + i)
            return acc

        conv = convert_function(f)
        assert conv is not None
        x = paddle.to_tensor(np.ones((2,), np.float32))
        # python int bound: python-path while, same math as the original
        np.testing.assert_allclose(
            conv(x, 3).numpy(), f(x, 3).numpy(), rtol=1e-6)
        # tensor bound under jit: lax.while_loop path
        import jax

        def run(nv):
            n_t = paddle.to_tensor(nv)
            return conv(x, n_t)._data

        out = jax.jit(run)(np.asarray(3, np.int32))
        np.testing.assert_allclose(np.asarray(out), f(x, 3).numpy(),
                                   rtol=1e-5)

    def test_for_over_layerlist_untouched(self):
        """for blk in self.blocks must stay a Python loop (trace
        unrolls it) — only range() iterations convert."""

        class Stack(nn.Layer):
            def __init__(self):
                super().__init__()
                self.blocks = nn.LayerList([nn.Linear(3, 3)
                                            for _ in range(2)])

            def forward(self, x):
                if paddle.sum(x) > 0:   # ensures counter > 0
                    y = x * 1.0
                else:
                    y = x * 2.0
                for blk in self.blocks:
                    y = blk(y)
                return paddle.sum(y)

        paddle.seed(9)
        net = Stack()
        xv = paddle.to_tensor(np.ones((2, 3), np.float32))
        eager = float(net.forward(xv).numpy())
        st = to_static(net)
        static = float(st(xv).numpy())
        np.testing.assert_allclose(eager, static, rtol=1e-5)


class BreakWhileNet(nn.Layer):
    """Tensor-dependent break (reference:
    break_continue_transformer.py test_break_continue.py patterns)."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(3, 3)

    def forward(self, x):
        h = self.fc(x)
        s = paddle.zeros([3], "float32")
        i = paddle.to_tensor(np.zeros((), np.float32))
        while i < 10.0:
            s = s + paddle.mean(h, axis=0)
            if paddle.sum(s) > 3.0:
                break
            i = i + 1.0
        return paddle.sum(s) + i


class ContinueForNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(3, 3)

    def forward(self, x):
        h = self.fc(x)
        s = paddle.zeros([], "float32")
        t = paddle.zeros([], "float32")
        for i in range(6):
            if paddle.sum(h) > 0:
                s = s + paddle.mean(h)
                continue
            t = t + 1.0
        return s - t


class BreakContinueForNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(3, 3)

    def forward(self, x):
        h = self.fc(x)
        s = paddle.zeros([], "float32")
        skipped = paddle.zeros([], "float32")
        for i in range(8):
            if paddle.mean(h) * (i + 1) > 2.0:
                break
            if paddle.sum(h) < 0:
                skipped = skipped + 1.0
                continue
            s = s + paddle.mean(h)
        return s * 10.0 + skipped


class TestBreakContinue:
    def test_tensor_break_in_while(self):
        x = np.random.RandomState(3).randn(2, 3).astype(np.float32)
        _eager_vs_static(BreakWhileNet, x)

    def test_tensor_continue_in_for(self):
        for seed in (0, 7):  # exercises both the continue and else path
            x = np.random.RandomState(seed).randn(2, 3).astype(np.float32)
            _eager_vs_static(ContinueForNet, x)

    def test_tensor_break_and_continue_in_for(self):
        for seed in (0, 5, 11):
            x = np.random.RandomState(seed).randn(2, 3).astype(np.float32)
            _eager_vs_static(BreakContinueForNet, x)

    def test_python_break_continue_semantics_preserved(self):
        """The flag rewrite must be a no-op semantically for plain
        Python values (conversion happens, control flow identical)."""

        def g(n):
            total = 0
            hit = 0
            for i in range(n):
                if i == 3:
                    continue
                if i > 6:
                    break
                total = total + i
            while total > 0:
                total = total - 5
                if total < -2:
                    break
                hit = hit + 1
            return total, hit

        conv = convert_function(g)
        assert conv is not None
        for n in (0, 1, 5, 10):
            assert conv(n) == g(n), n

    def test_nested_loop_break_binds_inner(self):
        def g(n):
            out = []
            for i in range(n):
                for j in range(10):
                    if j >= i:
                        break
                    out.append((i, j))
                if i > 2:
                    break
            return out

        conv = convert_function(g)
        assert conv is not None
        assert conv(6) == g(6)

    def test_trailing_statements_guarded(self):
        """Statements after a conditional break must not run once the
        flag is set — the bubbling guard."""

        def g(xs):
            seen = 0
            for i in range(len(xs)):
                if xs[i] < 0:
                    break
                seen = seen + 1
            return seen

        conv = convert_function(g)
        assert conv is not None
        assert conv([1, 2, -1, 4]) == 2
        assert conv([1, 2]) == 2

    def test_break_in_try_falls_back(self):
        """Exits inside try interact with handler semantics — the loop
        stays unconverted (Python behavior preserved)."""

        def g(n):
            s = 0
            for i in range(n):
                try:
                    if i > 2:
                        break
                    s += i
                except ValueError:
                    pass
            return s

        conv = convert_function(g)
        # conversion may return None (nothing else converted); either
        # way Python semantics hold
        fn = conv or g
        assert fn(6) == g(6)


class TestReturnInLoop:
    def test_python_pred_return_in_loop(self):
        def g(n):
            acc = 0
            for i in range(n):
                acc = acc + i
                if acc > 5:
                    return acc * 100
            return acc

        conv = convert_function(g)
        assert conv is not None
        for n in (0, 2, 4, 8):
            assert conv(n) == g(n), n

    def test_return_in_while_with_trailing_code(self):
        def g(x):
            i = 0
            while i < 10:
                i = i + 1
                if i * x > 12:
                    return -1
            y = i * 2
            return y

        conv = convert_function(g)
        assert conv is not None
        assert conv(5) == g(5) == -1
        assert conv(0) == g(0) == 20

    def test_eager_tensor_pred_return_in_loop(self):
        """Eager (concrete) tensor predicates pick real branches, so
        return-in-loop works without tracing."""

        def g(h):
            s = paddle.zeros([], "float32")
            for i in range(6):
                s = s + paddle.mean(h)
                if paddle.sum(s) > 2.0:
                    return s * 10.0
            return s

        conv = convert_function(g)
        assert conv is not None
        h = paddle.to_tensor(np.full((3,), 1.5, np.float32))
        np.testing.assert_allclose(conv(h).numpy(), g(h).numpy())
        h2 = paddle.to_tensor(np.full((3,), -0.1, np.float32))
        np.testing.assert_allclose(conv(h2).numpy(), g(h2).numpy())

    def test_traced_tensor_return_raises_guided(self):
        class RetNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(3, 3)

            def forward(self, x):
                h = self.fc(x)
                s = paddle.zeros([], "float32")
                for i in range(4):
                    s = s + paddle.mean(h)
                    if paddle.sum(s) > 1.0:
                        return s * 2.0
                return s

        paddle.seed(0)
        net = RetNet()
        st = to_static(net)
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        with pytest.raises(Exception) as ei:
            st(x)
        assert "result variable" in str(ei.value) or \
            "pre-loop binding" in str(ei.value) or \
            "Initialize" in str(ei.value)

    def test_return_in_nested_loop_falls_back(self):
        def g(n):
            for i in range(n):
                for j in range(n):
                    if i * j > 4:
                        return i + j
            return -1

        conv = convert_function(g)
        fn = conv or g
        assert fn(4) == g(4)
        assert fn(1) == g(1)


class TestExitReviewRegressions:
    def test_induction_value_after_break(self):
        """break leaves i at the break-iteration value, not one-past."""

        def g():
            for i in range(10):
                if i == 3:
                    break
            return i

        conv = convert_function(g)
        assert conv is not None
        assert conv() == g() == 3

    def test_induction_value_after_break_negative_step(self):
        def g():
            for i in range(9, -1, -2):
                if i < 4:
                    break
            return i

        conv = convert_function(g)
        assert conv is not None
        assert conv() == g() == 3

    def test_tensor_break_without_tensor_carry(self):
        """Loop vars start all-Python; the flag becomes traced on
        iteration 1 and the loop must re-dispatch, not crash."""

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(3, 3)

            def forward(self, x):
                h = self.fc(x)
                for i in range(8):
                    if paddle.mean(h) * (i + 1) > 2.0:
                        break
                return paddle.mean(h) * i

        for seed, scale in ((0, 3.0), (1, 0.01)):
            x = np.full((2, 3), scale, np.float32)
            paddle.seed(seed)
            net = Net()
            eager = float(net(x if isinstance(x, np.ndarray) else x).numpy()
                          if not isinstance(x, np.ndarray)
                          else net(paddle.to_tensor(x)).numpy())
            st = to_static(net)
            comp = float(st(paddle.to_tensor(x)).numpy())
            np.testing.assert_allclose(eager, comp, rtol=1e-5)

    def test_user_typeerror_not_relabeled(self):
        """A genuine TypeError from the loop body surfaces as-is, not as
        the carry-mismatch guidance."""

        def g(x):
            i = paddle.to_tensor(np.zeros((), np.float32))
            while i < 3.0:
                len(None)  # user bug
                i = i + 1.0
            return i

        conv = convert_function(g)
        assert conv is not None
        import jax

        def traced(a):
            from paddle_tpu.core.tensor import Tensor
            return conv(Tensor(a))

        with pytest.raises(TypeError) as ei:
            jax.eval_shape(traced, jax.ShapeDtypeStruct((), np.float32))
        assert "loop carry" not in str(ei.value)


class TestPrintTransformer:
    def test_print_traced_tensor_fires_at_runtime(self, capfd):
        """print(tensor) inside @to_static lowers to jax.debug.print —
        it must fire on EVERY call with concrete values (an
        untransformed print fires once at trace time with tracers).
        reference: dygraph_to_static/print_transformer.py"""
        import paddle_tpu as paddle
        from paddle_tpu.jit import to_static

        @to_static
        def f(x):
            print("val:", x)
            return x * 2

        f(paddle.to_tensor(np.float32(3.0))).numpy()
        f(paddle.to_tensor(np.float32(4.0))).numpy()
        err_out = capfd.readouterr()
        txt = err_out.out + err_out.err
        assert "val: 3" in txt, txt
        assert "val: 4" in txt, txt
        assert "Traced" not in txt  # no tracer repr leaked

    def test_print_host_values_keep_builtin_semantics(self, capsys):
        from paddle_tpu.jit.dy2static import convert_print
        convert_print("a", 1, sep="-", end="!\n")
        assert capsys.readouterr().out == "a-1!\n"

    def test_print_sep_end_file_honored_when_traced(self, capfd):
        """Braces in values, custom sep/end, and file=sys.stderr all keep
        builtin-print semantics through the host callback."""
        import paddle_tpu as paddle
        from paddle_tpu.jit import to_static

        @to_static
        def f(x):
            import sys
            print("{curly}", x, sep="|", end=";")
            print(x, file=sys.stderr)
            return x + 1

        f(paddle.to_tensor(np.float32(2.0))).numpy()
        out = capfd.readouterr()
        assert "{curly}|2" in out.out and out.out.rstrip().endswith(";"), \
            out.out
        assert "2" in out.err, out.err


class TestAssertTransformer:
    def test_assert_traced_passes_and_fails_at_runtime(self):
        """assert on a traced predicate becomes a runtime check
        (reference assert_transformer.py -> Assert op); untransformed it
        would raise TracerBoolConversionError at trace time."""
        import pytest as _pytest
        import paddle_tpu as paddle
        from paddle_tpu.jit import to_static

        @to_static
        def f(x):
            assert x > 0, "need positive"
            return x * 3

        out = f(paddle.to_tensor(np.float32(2.0)))
        assert float(out.numpy()) == 6.0
        with _pytest.raises(Exception, match="need positive"):
            f(paddle.to_tensor(np.float32(-1.0))).numpy()

    def test_assert_host_value_keeps_plain_semantics(self):
        from paddle_tpu.jit.dy2static import convert_assert
        convert_assert(True)
        import pytest as _pytest
        with _pytest.raises(AssertionError, match="boom"):
            convert_assert(False, lambda: "boom")
        with _pytest.raises(AssertionError):
            convert_assert(0)

    def test_assert_in_unselected_branch_stays_silent(self):
        """convert_ifelse executes BOTH branches under a traced
        predicate; an assert (or print) in the branch the predicate did
        NOT select must not fire (gated on the branch-activity mask)."""
        import paddle_tpu as paddle
        from paddle_tpu.jit import to_static

        @to_static
        def f(x):
            if x > 0:
                assert x > 1, "pos branch"
                y = x * 2
            else:
                y = -x
            return y

        # else-path input: the true-branch assert must NOT abort
        out = f(paddle.to_tensor(np.float32(-5.0)))
        assert float(out.numpy()) == 5.0
        # true-path input violating the assert still aborts
        import pytest as _pytest
        with _pytest.raises(Exception, match="pos branch"):
            f(paddle.to_tensor(np.float32(0.5))).numpy()

    def test_print_in_unselected_branch_stays_silent(self, capfd):
        import paddle_tpu as paddle
        from paddle_tpu.jit import to_static

        @to_static
        def g(x):
            if x > 0:
                print("POSITIVE", x)
                y = x * 2
            else:
                print("NEGATIVE", x)
                y = -x
            return y

        g(paddle.to_tensor(np.float32(-3.0))).numpy()
        out = capfd.readouterr()
        txt = out.out + out.err
        assert "NEGATIVE" in txt and "POSITIVE" not in txt, txt

    def test_assert_msg_lazy_on_host(self):
        """Python's assert evaluates the message only on failure."""
        from paddle_tpu.jit import to_static
        import paddle_tpu as paddle
        calls = []

        @to_static
        def h(x):
            assert True, calls.append("evaluated") or "m"
            return x + 1

        # host predicate True: msg thunk must not run
        out = h(paddle.to_tensor(np.float32(1.0)))
        assert float(out.numpy()) == 2.0
        assert calls == [], calls
