"""Resilient multi-replica router (serving/router.py + routerd.py).

Three tiers, all CPU tier-1 (``router`` marker):

* unit: affinity hashing / rendezvous stability, circuit-breaker
  state machine, retry classification + seeded backoff, hedging over
  scripted fake replicas (no engine, no jax work);
* integration: ``InProcessReplica`` over real tiny engines — probe
  classification (healthy/degraded/draining/dead), failover of a
  queued-but-unstarted request off a replica declared dead, greedy
  resume-with-context parity;
* the seeded CHAOS STORM (acceptance): a 3-replica fleet under the
  mixed workload with one replica's transport on a seeded
  refuse/black-hole/disconnect schedule — every request delivered
  exactly ONCE (greedy token-identical to ``generate()`` despite
  mid-stream kills), the breaker trips and recovers through
  half-open, survivors' pools refcount to zero, and the SAME SEED
  replays the SAME routing/failover log.
"""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.models import GPTModel
from paddle_tpu.serving import (CircuitBreaker, Engine, FaultInjector,
                                InProcessReplica, NoReplicasAvailable,
                                ReplicaAbandoned, ReplicaHTTPError,
                                ReplicaUnavailable, RequestFailed,
                                Router, RouterPolicy, affinity_key)
from paddle_tpu.serving.faults import (NET_SITES, SITES, NetDisconnect,
                                       NetRefused, NetTimeout)
from paddle_tpu.serving.router import (CLOSED, DEAD, DEGRADED,
                                       DRAINING, HALF_OPEN, HEALTHY,
                                       OPEN)

pytestmark = pytest.mark.router


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0)
    m.eval()
    return m


def _registry():
    return monitor.StatRegistry()


def _fast_policy(**kw):
    kw.setdefault("seed", 0)
    kw.setdefault("backoff_base_s", 0.0005)
    kw.setdefault("backoff_cap_s", 0.002)
    kw.setdefault("breaker_cooldown_s", 0.0)
    return RouterPolicy(**kw)


class FakeReplica:
    """Scripted no-engine replica: generated token i is
    ``(last_prompt_token + i + 1) % 97`` (deterministic, resumable —
    a greedy resume from k emitted tokens continues the same series),
    with per-op failure scripts."""

    def __init__(self, name, fail=None, health=None, delay_s=0.0):
        self.name = name
        self.fail = dict(fail or {})      # op -> exception factory
        self.health = health or (lambda: {
            "queue_depth": 0, "slots_free": 4, "draining": False})
        self.delay_s = delay_s
        self.op = 0
        self.served = []
        self.aborted = 0
        self.payloads = []

    def probe(self):
        return self.health()

    @staticmethod
    def continuation(prompt, n):
        return [(int(prompt[-1]) + i + 1) % 97 for i in range(n)]

    def generate(self, payload, should_abort=None):
        t = self.op
        self.op += 1
        self.payloads.append(dict(payload))
        if self.delay_s:
            t0 = time.monotonic()
            while time.monotonic() - t0 < self.delay_s:
                if should_abort is not None and should_abort():
                    self.aborted += 1
                    raise ReplicaAbandoned(f"{self.name} aborted")
                time.sleep(0.001)
        if t in self.fail:
            raise self.fail[t]()
        gen = self.continuation(payload["prompt"],
                                payload["max_new_tokens"])
        self.served.append(t)
        return {"id": t, "ids": list(payload["prompt"]) + gen,
                "generated": gen, "ttft_ms": 0.5}


def _router(reps, **pol):
    return Router(reps, policy=_fast_policy(**pol),
                  kv_block_size=8, registry=_registry())


def _prompt_on(router, name, length=8):
    """A prompt whose rendezvous affinity target is ``name``."""
    reps = router._reps()
    for s in range(500):
        p = [(s * 7 + i) % 100 for i in range(length)]
        key = affinity_key(p, router.block_size())
        if router._affinity_target(key, reps).name == name:
            return p
    raise AssertionError(f"no prompt maps to {name}")


# ---------------------------------------------------------------------------
# affinity hashing + pick policy (pure unit)
# ---------------------------------------------------------------------------

def test_affinity_key_block_alignment():
    """The hash covers the longest block-aligned span only: prompts
    sharing an aligned system-prompt head hash equal, a difference
    INSIDE the span diverges, and short prompts hash whole."""
    sys_prompt = list(range(16))
    a = affinity_key(sys_prompt + [50, 51, 52], 8)
    b = affinity_key(sys_prompt + [60, 61], 8)
    assert a == b                       # tails differ only past 16
    assert a != affinity_key([1] + sys_prompt[1:] + [50], 8)
    # 19 tokens at bs=8 -> span 16: changing token 17 is invisible,
    # changing token 15 is not
    assert affinity_key(sys_prompt + [1, 2, 3], 8) == \
        affinity_key(sys_prompt + [9, 2, 3], 8)
    assert affinity_key([1, 2, 3], 8) != affinity_key([1, 2, 4], 8)


def test_rendezvous_stability_under_churn():
    """Removing a replica only remaps the keys IT owned; everyone
    else's prefix-cache affinity survives the churn."""
    r = _router({n: FakeReplica(n) for n in ("a", "b", "c")})
    keys = [[(s * 11 + i) % 100 for i in range(8)] for s in range(60)]
    before = {}
    for i, p in enumerate(keys):
        before[i] = r._affinity_target(
            affinity_key(p, 8), r._reps()).name
    assert len(set(before.values())) == 3  # all three used
    r.remove_replica("c")
    for i, p in enumerate(keys):
        after = r._affinity_target(affinity_key(p, 8),
                                   r._reps()).name
        if before[i] != "c":
            assert after == before[i]


def test_pick_affinity_with_load_fallback():
    """The affinity target wins while its probed queue is shallow;
    past the threshold the pick falls back to least-loaded."""
    load = {"a": 0, "b": 0}
    reps = {n: FakeReplica(n, health=lambda n=n: {
        "queue_depth": load[n], "slots_free": 4, "draining": False})
        for n in ("a", "b")}
    r = _router(reps, affinity_queue_threshold=3)
    r.probe_once()
    p = _prompt_on(r, "a")
    rep, how = r.pick(p)
    assert (rep.name, how) == ("a", "affinity")
    load["a"] = 10                       # hot shard: probed depth up
    r.probe_once()
    rep, how = r.pick(p)
    assert (rep.name, how) == ("b", "load")


def test_pick_excludes_draining_and_dead():
    r = _router({n: FakeReplica(n) for n in ("a", "b")})
    r.probe_once()
    pa = _prompt_on(r, "a")
    for state in (DRAINING, DEAD):
        r._replicas["a"].state = state
        rep, how = r.pick(pa)
        assert rep.name == "b"
    r._replicas["b"].state = DEAD
    with pytest.raises(NoReplicasAvailable):
        r.pick(pa)
    # degraded is routable as last resort
    r._replicas["a"].state = DEGRADED
    rep, how = r.pick(pa)
    assert (rep.name, how) == ("a", "last_resort")


def test_random_routing_arm_is_seeded():
    """affinity=False (the bench baseline) picks by seeded hash:
    deterministic per (seed, request, attempt), spread over the
    pool."""
    def run(seed):
        r = _router({n: FakeReplica(n) for n in ("a", "b", "c")},
                    affinity=False, seed=seed)
        return [r.generate([5, 6, 7], max_new_tokens=2)["replica"]
                for _ in range(12)]
    first = run(3)
    assert first == run(3)
    assert len(set(first)) > 1
    assert first != run(4)


# ---------------------------------------------------------------------------
# circuit breaker (pure unit)
# ---------------------------------------------------------------------------

def test_breaker_trip_halfopen_and_recovery():
    events = []
    b = CircuitBreaker(threshold=3, cooldown_s=0.03,
                       on_transition=events.append)
    assert b.state == CLOSED and b.peek()
    b.record_failure()
    b.record_failure()
    assert b.state == CLOSED             # not yet: consecutive < 3
    b.record_success()
    b.record_failure()
    b.record_failure()
    assert b.state == CLOSED             # success reset the streak
    b.record_failure()
    assert b.state == OPEN and b.trips == 1
    assert not b.peek() and not b.acquire()   # cooling down
    time.sleep(0.04)
    assert b.peek()
    assert b.acquire()                    # admits the ONE trial
    assert b.state == HALF_OPEN
    assert not b.acquire()                # second concurrent trial: no
    b.record_failure()                    # failed trial -> re-open
    assert b.state == OPEN and b.trips == 2
    time.sleep(0.04)
    assert b.acquire()
    b.record_success()                    # clean trial -> closed
    assert b.state == CLOSED and b.peek()
    assert events == [OPEN, HALF_OPEN, OPEN, HALF_OPEN, CLOSED]


def test_breaker_trips_through_router_and_probe_recovers():
    """Consecutive request failures trip the replica's breaker (picks
    skip it); a clean health probe against the cooled-open breaker
    re-admits traffic through half-open."""
    boom = {i: lambda: NetRefused("down") for i in range(2)}
    a = FakeReplica("a", fail=boom)
    b = FakeReplica("b")
    r = _router({"a": a, "b": b}, breaker_threshold=2, retry_max=1)
    r.probe_once()
    pa = _prompt_on(r, "a")
    # two requests, each failing over a->b, trip a's breaker
    for _ in range(2):
        out = r.generate(list(pa), max_new_tokens=2)
        assert out["replica"] == "b"
    assert r._replicas["a"].breaker.state == OPEN
    assert r.registry.get("router.breaker_trips_total").value == 1
    assert r.registry.get("router.breaker_state.a").value == 2
    # cooled (cooldown 0) + clean probe -> half-open
    r.probe_once()
    assert r._replicas["a"].breaker.state == HALF_OPEN
    assert r.registry.get("router.breaker_state.a").value == 1
    # the trial request (a serves op 3 fine) closes it
    out = r.generate(list(pa), max_new_tokens=2)
    assert out["replica"] == "a"
    assert r._replicas["a"].breaker.state == CLOSED
    trans = [e for e in r.route_log() if e[0] == "breaker"]
    assert trans == [("breaker", "a", OPEN),
                     ("breaker", "a", HALF_OPEN),
                     ("breaker", "a", CLOSED)]


# ---------------------------------------------------------------------------
# retry classification / backoff / hedging (fake replicas)
# ---------------------------------------------------------------------------

def test_retry_honors_retry_after_and_backoff_is_seeded():
    hint = 0.05
    a = FakeReplica("a", fail={0: lambda: ReplicaUnavailable(
        "shedding", retry_after=hint)})
    r = _router({"a": a}, retry_max=2)
    t0 = time.monotonic()
    out = r.generate([3, 4, 5], max_new_tokens=2)
    waited = time.monotonic() - t0
    assert out["replica"] == "a" and out["attempts"] == 2
    assert waited >= hint                # the 503's hint was honored
    assert r.registry.get("router.retries_total").value == 1
    # the jitter draw is a pure function of (seed, request, attempt)
    assert r._backoff(7, 2) == r._backoff(7, 2)
    assert r._backoff(7, 2) != r._backoff(8, 2)
    assert Router({}, policy=_fast_policy(seed=0),
                  registry=_registry())._backoff(7, 2) == \
        r._backoff(7, 2)


def test_non_retryable_4xx_fails_fast():
    calls = []
    a = FakeReplica("a")
    a.fail = {i: lambda: ReplicaHTTPError("bad prompt", 400,
                                          reason="bad_request")
              for i in range(5)}
    orig = a.generate
    a.generate = lambda *aa, **kw: (calls.append(1),
                                    orig(*aa, **kw))[1]
    r = _router({"a": a, "b": FakeReplica("b")}, retry_max=3)
    pa = _prompt_on(r, "a")
    with pytest.raises(RequestFailed) as ei:
        r.generate(list(pa), max_new_tokens=2)
    assert isinstance(ei.value.cause, ReplicaHTTPError)
    assert len(calls) == 1               # 4xx never re-dispatches
    assert r.registry.get("router.retries_total").value == 0


def test_blackhole_timeout_retries_only_idempotent():
    """A lost response MAY mean executed work: greedy (and seeded)
    requests re-send, unseeded sampled requests fail fast."""
    def mk():
        a = FakeReplica("a", fail={0: lambda: NetTimeout("void")})
        return _router({"a": a, "b": FakeReplica("b")}, retry_max=2), a
    r, a = mk()
    pa = _prompt_on(r, "a")
    out = r.generate(list(pa), max_new_tokens=2)   # greedy: retried
    assert out["attempts"] == 2
    r2, a2 = mk()
    with pytest.raises(RequestFailed):
        r2.generate(list(pa), max_new_tokens=2, top_p=0.9)  # sampled,
        #   no seed: not idempotent, not blindly re-sent
    out = r2.generate(list(pa), max_new_tokens=2, top_p=0.9,
                      seed=11)            # seeded: idempotent again
    assert out["attempts"] == 1           # (op 1: no fault scripted)


def test_disconnect_resume_greedy_vs_restart_sampled():
    """Mid-body disconnect: greedy failover resumes from the emitted
    context (delivered stream identical to uninterrupted); sampled
    requests restart from scratch (emitted tokens discarded)."""
    p = [10, 11, 12]
    whole = FakeReplica.continuation(p, 6)

    def mk(**gen_kw):
        a = FakeReplica("a", fail={0: lambda: NetDisconnect(
            "mid-body", emitted=whole[:2])})
        b = FakeReplica("b")
        r = _router({"a": a, "b": b})
        pa = _prompt_on(r, "a")  # ensure the pick lands on a first
        return r, a, b
    r, a, b = mk()
    pa = _prompt_on(r, "a")
    whole_pa = FakeReplica.continuation(pa, 6)
    a.fail = {0: lambda: NetDisconnect("mid-body",
                                       emitted=whole_pa[:2])}
    out = r.generate(list(pa), max_new_tokens=6)
    assert out["generated"] == whole_pa           # seam-free resume
    assert b.payloads[0]["prompt"] == list(pa) + whole_pa[:2]
    assert b.payloads[0]["max_new_tokens"] == 4
    assert r.registry.get("router.failovers_total").value == 1
    # sampled+seeded: restart whole, nothing salvaged
    r2, a2, b2 = mk()
    pa2 = _prompt_on(r2, "a")
    a2.fail = {0: lambda: NetDisconnect(
        "mid-body", emitted=FakeReplica.continuation(pa2, 6)[:2])}
    r2.generate(list(pa2), max_new_tokens=6, top_p=0.9, seed=5)
    assert b2.payloads[0]["prompt"] == list(pa2)
    assert b2.payloads[0]["max_new_tokens"] == 6


def test_hedge_fires_after_delay_and_cancels_loser():
    """Tail-latency hedging: a slow primary gets a delayed second
    dispatch; the fast winner returns, the loser is cancelled via its
    abort hook, and the metrics/log record the hedge win."""
    reps = {"a": FakeReplica("a"), "b": FakeReplica("b")}
    r = _router(reps, hedge=True, hedge_after_s=0.03)
    r.probe_once()
    pa = _prompt_on(r, "a")
    reps["a"].delay_s = 0.5               # primary: slow
    reps["b"].delay_s = 0.0
    out = r.generate(list(pa), max_new_tokens=3)
    assert out["replica"] == "b"
    assert out["generated"] == FakeReplica.continuation(pa, 3)
    # the fired hedge was a real second dispatch: attempts counts it
    assert out["attempts"] == 2
    assert r.registry.get("router.hedges_total").value == 1
    assert r.registry.get("router.hedge_wins_total").value == 1
    for _ in range(100):                  # loser observes its abort
        if reps["a"].aborted:
            break
        time.sleep(0.005)
    assert reps["a"].aborted == 1
    kinds = [e[0] for e in r.route_log()]
    assert "hedge" in kinds and "hedge_win" in kinds
    # a hedge-cancelled primary is NOT a breaker failure
    assert reps["a"].name not in [
        e[1] for e in r.route_log() if e[0] == "breaker"]
    assert r._replicas["a"].breaker.failures == 0


def test_hedge_default_p99_delay_path():
    """``RouterPolicy(hedge=True)`` with the DEFAULT p99-derived
    delay (hedge_after_s=None) — the README's own example — must
    work: the floor applies until enough latency samples exist."""
    reps = {"a": FakeReplica("a"), "b": FakeReplica("b")}
    r = _router(reps, hedge=True, hedge_floor_s=0.02)
    r.probe_once()
    pa = _prompt_on(r, "a")
    reps["a"].delay_s = 0.5
    out = r.generate(list(pa), max_new_tokens=2)
    assert out["replica"] == "b"
    assert r.registry.get("router.hedge_wins_total").value == 1


def test_hedge_is_the_halfopen_trial():
    """A hedge dispatched at a recovering replica consumes its
    HALF_OPEN trial slot like any other dispatch: the transition log
    shows open -> half_open -> closed, never open -> closed (a hedge
    that skipped acquire would race the single-trial invariant)."""
    reps = {"a": FakeReplica("a"), "b": FakeReplica("b")}
    r = _router(reps, hedge=True, hedge_after_s=0.02,
                breaker_threshold=1)
    r.probe_once()
    pa = _prompt_on(r, "a")
    r._replicas["b"].breaker.record_failure()   # OPEN; cooldown 0
    reps["a"].delay_s = 0.3
    out = r.generate(list(pa), max_new_tokens=2)
    assert out["replica"] == "b"            # the hedge WAS the trial
    trans = [s for (_, name, s) in
             (e for e in r.route_log() if e[0] == "breaker")
             if name == "b"]
    assert trans == [OPEN, HALF_OPEN, CLOSED]


def test_probe_sweep_not_blocked_by_hung_replicas():
    """Probes go out concurrently: hung replicas must not head-of-
    line block health detection for the rest of the fleet (sweep
    cost ~max over replicas, not the sum)."""
    def hang(delay):
        def health():
            time.sleep(delay)
            return {"queue_depth": 0, "slots_free": 4}
        return health
    r = _router({"s1": FakeReplica("s1", health=hang(0.4)),
                 "s2": FakeReplica("s2", health=hang(0.4)),
                 "fast": FakeReplica("fast")})
    t0 = time.monotonic()
    out = r.probe_once()
    dt = time.monotonic() - t0
    assert set(out.values()) == {HEALTHY}
    assert dt < 0.75                      # serial would be >= 0.8


def test_router_spans_and_lifecycle_instants():
    a = FakeReplica("a", fail={0: lambda: NetRefused("down")})
    r = _router({"a": a, "b": FakeReplica("b")}, retry_max=1)
    r.probe_once()
    pa = _prompt_on(r, "a")
    r.generate(list(pa), max_new_tokens=2)
    events = r.chrome_trace()["traceEvents"]
    names = {e["name"] for e in events}
    assert {"probe", "route.pick", "route.accepted",
            "route.served", "route.failover"} <= names


# ---------------------------------------------------------------------------
# probe classification + failover off a dying replica (real engines)
# ---------------------------------------------------------------------------

def _engine(model, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("kv_block_size", 8)
    kw.setdefault("registry", _registry())
    return Engine(model, **kw)


def _fresh_model():
    """A private model instance with the SAME seeded weights as the
    ``tiny_gpt`` fixture.  Engines that may TRACE new programs
    concurrently (one replica decoding while another prefills) must
    not share a model: jax tracing is not thread-safe across threads
    sharing one compile cache.  Same seed => greedy outputs still
    match the fixture's ``generate()`` references."""
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0)
    m.eval()
    return m


def test_probe_states_from_real_engine(tiny_gpt):
    eng = _engine(tiny_gpt)
    rep = InProcessReplica("r0", eng)
    r = _router({"r0": rep}, dead_after=2)
    assert r.probe_once() == {"r0": HEALTHY}
    assert r.block_size() == 8            # adopted from the probe
    eng._draining = True
    assert r.probe_once() == {"r0": DRAINING}
    eng._draining = False
    eng._watchdog_fired = True
    assert r.probe_once() == {"r0": DEGRADED}
    eng._watchdog_fired = False
    rep.kill()
    assert r.probe_once() == {"r0": DEGRADED}   # first miss degrades
    assert r.probe_once() == {"r0": DEAD}       # dead_after=2 kills
    assert r.registry.get("router.replica_health.r0").value == 0
    rep.revive()
    assert r.probe_once() == {"r0": HEALTHY}
    assert r.registry.get("router.replica_health.r0").value == 3
    # the log records state CHANGES only (kill's first miss lands on
    # an already-degraded replica, so only the DEAD step logs)
    state_log = [e for e in r.route_log() if e[0] == "probe"]
    assert state_log == [("probe", "r0", DRAINING),
                         ("probe", "r0", DEGRADED),
                         ("probe", "r0", DEAD),
                         ("probe", "r0", HEALTHY)]


def test_unstarted_request_fails_over_off_dead_replica(tiny_gpt):
    """A request still QUEUED on a replica the router declares dead is
    abandoned (nothing emitted) and re-routed — delivered exactly
    once, by the survivor."""
    # a's engine loop is NEVER STARTED: the routed request sits in its
    # queue until the router declares a dead — deterministically
    # "queued-but-unstarted", with no wall-clock slot wedge that
    # full-suite CPU load could let finish early (private models: b
    # traces while the main thread runs the reference generate)
    ea, eb = _engine(_fresh_model()), _engine(_fresh_model())
    ra = InProcessReplica("a", ea)
    rb = InProcessReplica("b", eb)
    r = _router({"a": ra, "b": rb})
    r.probe_once()
    eb.start()
    try:
        pa = _prompt_on(r, "a")
        ref = tiny_gpt.generate(
            paddle.to_tensor(np.asarray([pa], np.int32)),
            max_new_tokens=6).numpy()[0]
        box = {}

        def call():
            box["out"] = r.generate(list(pa), max_new_tokens=6)

        t = threading.Thread(target=call, daemon=True)
        t.start()
        # wait until the request is actually queued on a (nothing
        # drains a's queue, so depth can only rise)
        queued = False
        for _ in range(5000):
            if ea.queue.depth() >= 1:
                queued = True
                break
            time.sleep(0.002)
        assert queued
        r.mark_dead("a")
        t.join(timeout=20)
        assert not t.is_alive()
        out = box["out"]
        assert out["replica"] == "b"
        assert out["ids"] == [int(x) for x in ref]
        assert ("failover", out["req"], "a", "abandoned") in \
            r.route_log()
        assert r.registry.get("router.failovers_total").value == 1
        serves = [e for e in r.route_log() if e[0] == "serve"]
        assert len(serves) == 1           # exactly once
    finally:
        ea.stop(drain=False)
        eb.stop(drain=False)


def test_draining_replica_stops_receiving_new_requests(tiny_gpt):
    """Cooperative drain: a replica reporting draining keeps its
    in-flight streams but the router routes new work elsewhere."""
    ea, eb = _engine(_fresh_model()), _engine(_fresh_model())
    r = _router({"a": InProcessReplica("a", ea),
                 "b": InProcessReplica("b", eb)})
    r.probe_once()
    ea.start()
    eb.start()
    try:
        pa = _prompt_on(r, "a")
        assert r.generate(list(pa), max_new_tokens=2)["replica"] == "a"
        ea._draining = True               # stop(drain=True) mid-flight
        r.probe_once()
        for _ in range(3):
            out = r.generate(list(pa), max_new_tokens=2)
            assert out["replica"] == "b"
    finally:
        ea.stop(drain=False)
        eb.stop(drain=False)


# ---------------------------------------------------------------------------
# net fault sites (faults.py satellites)
# ---------------------------------------------------------------------------

def test_net_sites_pure_schedule_and_actions():
    assert set(NET_SITES) <= set(SITES)
    a = FaultInjector(seed=9, rates={"net_refuse": 0.4})
    b = FaultInjector(seed=9, rates={"net_refuse": 0.4})
    sched = [a.scheduled("net_refuse", t) for t in range(100)]
    assert sched == [b.scheduled("net_refuse", t) for t in range(100)]
    assert 10 <= sum(sched) <= 80
    inj = FaultInjector(seed=0, blackhole_s=0.0)
    with pytest.raises(NetRefused):
        inj.fire("net_refuse", 3)
    with pytest.raises(NetTimeout):
        inj.fire("net_blackhole", 4)
    with pytest.raises(NetDisconnect) as ei:
        inj.fire("net_disconnect", 5, emitted=[7, 8])
    assert ei.value.emitted == [7, 8]
    inj.fire("net_slow", 6)               # proceeds after the sleep
    assert inj.log == [(3, "net_refuse"), (4, "net_blackhole"),
                       (5, "net_disconnect"), (6, "net_slow")]


def test_blackhole_abort_hook_cuts_the_wait_short():
    inj = FaultInjector(seed=0, blackhole_s=5.0)
    t0 = time.monotonic()
    with pytest.raises(NetTimeout):
        inj.fire("net_blackhole", 0, abort=lambda: True)
    assert time.monotonic() - t0 < 1.0


# ---------------------------------------------------------------------------
# the seeded chaos storm (acceptance)
# ---------------------------------------------------------------------------

def _storm_workload():
    """Mixed, deterministic: shared 8-token system prompt (one
    affinity class) + unique tails, varying lengths, greedy AND
    seeded-sampled traffic."""
    rng = np.random.RandomState(42)
    sys_prompt = rng.randint(0, 128, (8,)).tolist()
    jobs = []
    for i in range(14):
        tail = rng.randint(0, 128, (1 + i % 5,)).tolist()
        kw = {"max_new_tokens": 3 + i % 6}
        if i % 4 == 3:
            kw.update(top_p=0.9, temperature=0.8, seed=1000 + i)
        jobs.append((sys_prompt + tail, kw))
    return jobs


def _run_storm(tiny_gpt, seed):
    """One full storm run on fresh engines; returns everything the
    determinism/exactly-once assertions need."""
    engines = [_engine(tiny_gpt) for _ in range(3)]
    injs = [FaultInjector(seed=seed * 10 + i, blackhole_s=0.0,
                          net_slow_s=0.001)
            for i in range(3)]
    reps = {f"r{i}": InProcessReplica(f"r{i}", engines[i],
                                      faults=injs[i])
            for i in range(3)}
    r = Router(reps, policy=_fast_policy(
        seed=seed, retry_max=5, breaker_threshold=2,
        affinity_queue_threshold=64), kv_block_size=8,
        registry=_registry())
    # the whole workload shares one system prompt = ONE affinity
    # class: make ITS target the sick replica (refuse / black-hole /
    # mid-stream disconnect on a seeded schedule) so the storm rains
    # where the traffic lands; one bystander is merely slow
    sys_prompt = _storm_workload()[0][0][:8]
    sick = r._affinity_target(affinity_key(sys_prompt, 8),
                              r._reps()).name
    slow = next(n for n in ("r0", "r1", "r2") if n != sick)
    injs[int(sick[1])].rates = {"net_refuse": 0.30,
                                "net_blackhole": 0.15,
                                "net_disconnect": 0.25}
    # windowed storm: ops past the window are clean, so the tail of
    # the workload deterministically exercises breaker RECOVERY (a
    # half-open trial that finally succeeds), not just tripping
    injs[int(sick[1])].last_tick = 10
    injs[int(slow[1])].rates = {"net_slow": 0.2}
    for e in engines:
        e.start()
    def settle():
        # wait for every engine to go fully idle before probing: a
        # probe racing the engine thread's slot release would read a
        # timing-dependent slots_free, and the least-loaded tie-break
        # would fork the routing log between identically-seeded runs
        for e in engines:
            for _ in range(5000):
                if e.scheduler.idle() and e.queue.depth() == 0:
                    break
                time.sleep(0.002)

    outs = []
    try:
        settle()
        r.probe_once()
        for prompt, kw in _storm_workload():
            outs.append(r.generate(list(prompt), **kw))
            settle()
            r.probe_once()                # deterministic probe cadence
    finally:
        # let orphaned work (streams the router abandoned mid-fault)
        # finish before shutdown so pool invariants are checkable
        for e in engines:
            for _ in range(2000):
                if e.scheduler.idle() and e.queue.depth() == 0:
                    break
                time.sleep(0.002)
            e.stop(drain=False)
    leaks = []
    for e in engines:
        if e.prefix_cache is not None:
            e.prefix_cache.clear()
        leaks.append(e.block_pool.in_use())
    return {
        "outs": outs,
        "sick": sick,
        "route_log": r.route_log(),
        "fault_logs": [list(i.log) for i in injs],
        "breaker_events": [e for e in r.route_log()
                           if e[0] == "breaker"],
        "leaks": leaks,
        "retries": r.registry.get("router.retries_total").value,
        "failovers": r.registry.get("router.failovers_total").value,
    }


@pytest.mark.chaos
def test_chaos_storm_exactly_once_and_deterministic(tiny_gpt):
    """THE acceptance storm: a replica killed/black-holed mid-stream
    under the mixed workload.  Every request is delivered exactly
    once (greedy results token-identical to ``generate()`` despite
    failovers — no losses, no duplicates, no cross-replica
    corruption), the sick replica's breaker trips and recovers
    through half-open, survivors' pools refcount to zero, and the
    same seed replays the same fault AND routing/failover logs."""
    run1 = _run_storm(tiny_gpt, seed=7)
    # --- delivery: exactly once, content-correct ---------------------
    jobs = _storm_workload()
    assert len(run1["outs"]) == len(jobs)
    serves = [e for e in run1["route_log"] if e[0] == "serve"]
    assert len(serves) == len(jobs)                  # one serve each
    assert len({e[1] for e in serves}) == len(jobs)  # ...per request
    for (prompt, kw), out in zip(jobs, run1["outs"]):
        assert len(out["generated"]) <= kw["max_new_tokens"]
        if "seed" not in kw:                         # greedy: exact
            ref = tiny_gpt.generate(
                paddle.to_tensor(np.asarray([prompt], np.int32)),
                max_new_tokens=kw["max_new_tokens"]).numpy()[0]
            assert out["ids"] == [int(x) for x in ref]
    # --- the storm actually stormed ----------------------------------
    sick = run1["sick"]
    assert run1["retries"] >= 3
    assert run1["failovers"] >= 1
    assert run1["fault_logs"][int(sick[1])]
    # --- breaker tripped AND recovered through half-open -------------
    states = [s for (_, name, s) in run1["breaker_events"]
              if name == sick]
    assert OPEN in states, "the sick replica never tripped its breaker"
    assert HALF_OPEN in states
    assert CLOSED in states[states.index(HALF_OPEN):], \
        "breaker never recovered through half-open"
    # --- no leaks on any replica (survivors AND the sick one) --------
    assert run1["leaks"] == [0, 0, 0]
    # --- same seed => same fault schedule, same routing log ----------
    run2 = _run_storm(tiny_gpt, seed=7)
    assert run2["fault_logs"] == run1["fault_logs"]
    assert run2["route_log"] == run1["route_log"]
    assert [o["ids"] for o in run2["outs"]] == \
        [o["ids"] for o in run1["outs"]]
    assert [o["replica"] for o in run2["outs"]] == \
        [o["replica"] for o in run1["outs"]]
    # --- a different seed diverges somewhere -------------------------
    run3 = _run_storm(tiny_gpt, seed=8)
    assert (run3["fault_logs"] != run1["fault_logs"]
            or run3["route_log"] != run1["route_log"])
    # seeded-sampled outputs are reproducible across storms with
    # DIFFERENT fault schedules too: a replica change or a restart
    # must not fork a seeded stream
    for (prompt, kw), o1, o3 in zip(jobs, run1["outs"],
                                    run3["outs"]):
        if "seed" in kw:
            assert o1["ids"] == o3["ids"]


def test_classify_probe_handles_both_healthz_shapes():
    """DRAINING must be detected from httpd's /healthz shape (a
    "state" field, no top-level "draining" key) as well as
    InProcessReplica's bool — an HTTP replica in stop(drain=True)
    must not be misread as merely degraded (degraded is routable as
    last resort; draining never is)."""
    r = Router({}, policy=_fast_policy(), registry=_registry())
    # httpd /healthz shape
    assert r.classify_probe({"status": "ok", "live": True,
                             "ready": False,
                             "state": DRAINING}) == DRAINING
    assert r.classify_probe({"live": True, "ready": False,
                             "state": "watchdog_fired",
                             "watchdog_fired": True}) == DEGRADED
    assert r.classify_probe({"status": "ok", "live": True,
                             "ready": True, "state": "ok"}) == HEALTHY
    # InProcessReplica shape
    assert r.classify_probe({"draining": True}) == DRAINING
    assert r.classify_probe({"watchdog_fired": True}) == DEGRADED
    assert r.classify_probe({"status": "ok"}) == HEALTHY


def test_4xx_is_caller_fault_not_a_breaker_failure():
    """A 4xx reply PROVES the replica is answering: it must not trip
    the breaker (a bad client would otherwise blackball a healthy
    replica for everyone)."""
    a = FakeReplica("a", fail={i: (lambda: ReplicaHTTPError(
        "bad prompt", 400, reason="bad_request")) for i in range(4)})
    r = _router({"a": a}, breaker_threshold=2)
    for _ in range(4):
        with pytest.raises(RequestFailed):
            r.generate([1, 2, 3], max_new_tokens=2)
    assert r._replicas["a"].breaker.state == CLOSED
    assert r.registry.get("router.breaker_trips_total").value == 0


def test_inprocess_caller_fault_maps_to_400_not_breaker(tiny_gpt):
    """Engine-side argument validation (a bad seed) through the
    IN-PROCESS transport is the caller's fault too — surfaced as a
    non-retryable 400 exactly like httpd would send, never fed to the
    replica's breaker (the HTTP transport's 4xx rule, mirrored; a bad
    client must not blackball a healthy replica on any transport)."""
    eng = _engine(tiny_gpt)
    r = _router({"r0": InProcessReplica("r0", eng)},
                breaker_threshold=2)
    r.probe_once()
    for _ in range(3):
        with pytest.raises(RequestFailed) as ei:
            r.generate([1, 2, 3], max_new_tokens=2, seed=-1)
        assert isinstance(ei.value.cause, ReplicaHTTPError)
        assert ei.value.cause.status == 400
        assert ei.value.cause.reason == "bad_request"
    assert r._replicas["r0"].breaker.state == CLOSED
    assert r.registry.get("router.retries_total").value == 0
    assert r.registry.get("router.breaker_trips_total").value == 0


def test_cancelled_attempt_releases_halfopen_trial():
    """A router-cancelled attempt (hedge loser, shutdown) during a
    HALF_OPEN trial releases the trial slot — neither success nor
    failure — so the breaker cannot wedge in HALF_OPEN forever."""
    b = CircuitBreaker(threshold=1, cooldown_s=0.0)
    b.record_failure()
    assert b.state == OPEN
    assert b.acquire()                   # HALF_OPEN, trial in flight
    b.release_trial()
    assert b.state == HALF_OPEN and b.peek()
    assert b.acquire()                   # the NEXT request can trial
    b.record_success()
    assert b.state == CLOSED
    # through the router's attempt path: an aborted dispatch on a
    # half-open replica hands the slot back
    a = FakeReplica("a", delay_s=0.5)
    r = _router({"a": a}, breaker_threshold=1)
    br = r._replicas["a"].breaker
    br.record_failure()
    assert br.acquire()
    assert br.state == HALF_OPEN
    failures_before = br.failures
    with pytest.raises(ReplicaAbandoned):
        r._attempt(r._replicas["a"],
                   {"prompt": [1], "max_new_tokens": 1}, rid=0,
                   abort_extra=lambda: True)
    assert br.state == HALF_OPEN and br.peek()
    assert br.failures == failures_before   # cancellation not counted


def test_http_retry_after_accepts_both_header_forms():
    """Retry-After is delta-seconds OR an HTTP-date (RFC 7231 —
    proxies emit the date form); unparseable values degrade to None
    instead of crashing the 503 handler."""
    import datetime
    from email.utils import format_datetime
    from paddle_tpu.serving import HttpReplicaClient
    c = HttpReplicaClient("http://nowhere")
    assert c._retry_after_s("1.5") == 1.5
    assert c._retry_after_s(None) is None
    assert c._retry_after_s("not a date") is None
    future = (datetime.datetime.now(datetime.timezone.utc)
              + datetime.timedelta(seconds=30))
    got = c._retry_after_s(format_datetime(future, usegmt=True))
    assert got is not None and 0.0 <= got <= 31.0
    past = (datetime.datetime.now(datetime.timezone.utc)
            - datetime.timedelta(seconds=30))
    assert c._retry_after_s(format_datetime(past, usegmt=True)) == 0.0


def test_disconnect_after_eos_does_not_redispatch():
    """A salvaged stream that already ends in EOS is WHOLE: resuming
    it would generate past the EOS — the router must serve it as-is
    even though max_new_tokens is not exhausted."""
    a = FakeReplica("a")
    b = FakeReplica("b")
    r = _router({"a": a, "b": b})
    pa = _prompt_on(r, "a")
    a.fail = {0: lambda: NetDisconnect("mid-body",
                                       emitted=[20, 30, 7])}
    out = r.generate(list(pa), max_new_tokens=6, eos_token_id=7)
    assert out["generated"] == [20, 30, 7]
    assert b.payloads == []        # nothing re-dispatched past EOS
    assert a.payloads[0]["eos_token_id"] == 7
    # "attempts" counts DISPATCHES: one was made (it disconnected but
    # delivered the whole stream), none re-dispatched
    assert out["attempts"] == 1


def test_caller_timeout_caps_attempt_transport_budget():
    """A caller deadline shrinks each attempt's transport timeout —
    one slow attempt must not overrun the caller's budget by the
    policy-wide 60s default."""
    a = FakeReplica("a")
    r = _router({"a": a}, request_timeout_s=60.0)
    r.generate([1, 2, 3], max_new_tokens=2, timeout=0.5)
    assert a.payloads[0]["timeout_s"] <= 0.5
    r.generate([1, 2, 3], max_new_tokens=2)
    assert a.payloads[1]["timeout_s"] == 60.0   # no deadline: policy


def test_http_client_maps_connect_phase_reset_retryable():
    """A URLError WRAPPING a connection reset (replica died
    mid-handshake) maps to NetDisconnect — retryable like any other
    transport death, not an anonymous non-retryable error."""
    import urllib.error
    from paddle_tpu.serving import HttpReplicaClient
    c = HttpReplicaClient("http://nowhere")
    got = c._map_net(urllib.error.URLError(
        ConnectionResetError(104, "reset by peer")), "generate")
    assert isinstance(got, NetDisconnect)
    got = c._map_net(urllib.error.URLError(
        ConnectionRefusedError(111, "refused")), "generate")
    assert isinstance(got, NetRefused)


def test_routerd_replica_spec_parsing():
    """NAME=URL splits on the first '=' ONLY when the left side is a
    name — a bare URL with '=' in its query string stays whole."""
    from paddle_tpu.serving.routerd import parse_replica_spec
    assert parse_replica_spec("a=http://h:1") == ("a", "http://h:1")
    assert parse_replica_spec("http://h:8000") == \
        ("h:8000", "http://h:8000")
    assert parse_replica_spec("http://h:8000/v1?key=abc") == \
        ("h:8000/v1?key=abc", "http://h:8000/v1?key=abc")


def test_routerd_main_fails_fast_when_no_replica_answers():
    """A fleet where NO replica answers its first probe is a
    configuration error (typo'd address): routerd exits instead of
    serving guaranteed 503s."""
    from paddle_tpu.serving import routerd
    with pytest.raises(SystemExit):
        routerd.main(["--replica", "http://127.0.0.1:9",
                      "--port", "0"])


# ---------------------------------------------------------------------------
# routerd: the HTTP front door (fake replicas over a real socket)
# ---------------------------------------------------------------------------

def _http(method, url, body=None, timeout=5.0):
    import json
    import urllib.error
    import urllib.request
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(
                resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_routerd_endpoints_and_json_error_contract():
    """RouterServer speaks the router's whole surface over a real
    socket — /generate (carrying ``replica`` + ``attempts``),
    /healthz, /livez, /readyz, /replicas, /metrics — and every error
    is JSON with a machine-readable ``reason``."""
    from paddle_tpu.serving import RouterServer
    reps = {"a": FakeReplica("a"), "b": FakeReplica("b")}
    r = _router(reps, probe_interval_s=0.02)
    with RouterServer(r, port=0) as srv:
        code, out, _ = _http("POST", srv.address + "/generate",
                             {"prompt": [3, 4, 5],
                              "max_new_tokens": 3})
        assert code == 200
        assert out["generated"] == FakeReplica.continuation(
            [3, 4, 5], 3)
        assert out["replica"] in ("a", "b") and out["attempts"] == 1
        code, h, _ = _http("GET", srv.address + "/healthz")
        assert code == 200 and h["live"] and h["ready"]
        assert h["replicas_total"] == 2
        code, h, _ = _http("GET", srv.address + "/livez")
        assert code == 200 and h["live"]
        code, h, _ = _http("GET", srv.address + "/readyz")
        assert code == 200 and h["ready"]
        code, table, _ = _http("GET", srv.address + "/replicas")
        assert {row["name"] for row in table["replicas"]} == \
            {"a", "b"}
        import urllib.request
        with urllib.request.urlopen(srv.address + "/metrics",
                                    timeout=5.0) as resp:
            text = resp.read().decode()
            ctype = resp.headers.get("Content-Type", "")
        assert "router_requests_total 1" in text
        assert ctype.startswith("text/plain")
        code, body, _ = _http("GET", srv.address + "/nope")
        assert code == 404 and body["reason"] == "not_found"
        code, body, _ = _http("POST", srv.address + "/generate",
                              {"prompt": []})
        assert code == 400 and body["reason"] == "bad_request"
        # stdlib-generated errors (unsupported method) keep the JSON
        # contract AND close the connection: the unread PUT body must
        # not desync a keep-alive client into parsing it as the next
        # request line
        import http.client
        import json as _json
        conn = http.client.HTTPConnection(srv.host, srv.port,
                                          timeout=5.0)
        conn.request("PUT", "/generate", body=b'{"x": 1}',
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 501
        assert resp.headers.get("Content-Type") == "application/json"
        assert resp.headers.get("Connection") == "close"
        assert _json.loads(resp.read())["reason"] == "http_501"
        conn.close()
        # the whole fleet drains -> not ready, generate sheds with a
        # reason (the prober flips the states; poll its cadence)
        for rep in reps.values():
            rep.health = lambda: {"draining": True}
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            code, body, _ = _http("GET", srv.address + "/readyz")
            if code == 503:
                break
            time.sleep(0.01)
        assert code == 503 and body["reason"] == "no_replicas"
        code, body, _ = _http("POST", srv.address + "/generate",
                              {"prompt": [1, 2]})
        assert code == 503 and body["reason"] == "no_replicas"


def _load_timeline_tool():
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "timeline.py")
    spec = importlib.util.spec_from_file_location("timeline", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_timeline_expands_router_into_per_replica_sources(tmp_path):
    """tools/timeline.py --router expands a routerd base URL via its
    /replicas registry into the router's own trace plus one source
    per HTTP-addressable replica — one pid each in the merge, named
    by the registry row (a source's self-reported process_name is
    dropped: it carries a host pid, ambiguous on a shared host);
    replicas without a fetchable address are skipped, not fatal."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from paddle_tpu.serving import HttpReplicaClient, RouterServer

    replica_trace = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 4242, "tid": 0,
         "args": {"name": "paddle_tpu-serving pid=4242"}},
        {"name": "tick", "ph": "X", "ts": 1.0, "dur": 5.0,
         "pid": 4242, "tid": 0, "cat": "serving"}]}

    class Stub(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            obj = ({"status": "ok", "queue_depth": 0, "slots_free": 2}
                   if self.path == "/healthz" else replica_trace)
            data = json.dumps(obj).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    stub = ThreadingHTTPServer(("127.0.0.1", 0), Stub)
    threading.Thread(target=stub.serve_forever, daemon=True).start()
    stub_url = f"http://127.0.0.1:{stub.server_address[1]}"
    try:
        # "gone" has a fetchable-LOOKING address but nothing answers:
        # the replica-kill scenario — the merge must skip it with a
        # note, not crash with no timeline at all
        r = _router({"web": HttpReplicaClient(stub_url),
                     "local": FakeReplica("local"),
                     "gone": HttpReplicaClient("http://127.0.0.1:9")},
                    probe_interval_s=30.0)
        r.probe_once()                  # router trace gets probe spans
        tl = _load_timeline_tool()
        with RouterServer(r, port=0) as srv:
            pairs = tl.router_sources(srv.address)
            assert [lbl for lbl, _ in pairs] == \
                ["router", "replica:web", "replica:gone"]
            assert pairs[1][1] == stub_url + "/debug/trace"
            out = tmp_path / "fleet.json"
            assert tl.main(["--router", srv.address,
                            "--out", str(out)]) == 0
        import json as _json
        merged = _json.loads(out.read_text())
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == {0, 1}
        names = {(e["pid"], e["args"]["name"])
                 for e in merged["traceEvents"]
                 if e.get("name") == "process_name"}
        assert names == {(0, "router"), (1, "replica:web")}
        assert any(e.get("name") == "probe" and e["pid"] == 0
                   for e in merged["traceEvents"])
        assert any(e.get("name") == "tick" and e["pid"] == 1
                   for e in merged["traceEvents"])
    finally:
        stub.shutdown()
        stub.server_close()


@pytest.mark.slow
def test_routerd_fleet_failover_over_real_sockets(tiny_gpt):
    """End-to-end over real sockets: two EngineServer replicas behind
    a routerd.  A request lands on its affinity target; that server
    dies; the next request pays one refused hop and fails over — the
    HTTP caller sees every request answered, token-identical to
    ``generate()``, and the fleet timeline merges router + replicas
    with one pid each."""
    from paddle_tpu.serving import (EngineServer, HttpReplicaClient,
                                    RouterServer)
    ea, eb = _engine(_fresh_model()), _engine(_fresh_model())
    sa = EngineServer(ea, port=0).start()
    sb = EngineServer(eb, port=0).start()
    killed_a = False
    try:
        r = _router({"a": HttpReplicaClient(sa.address),
                     "b": HttpReplicaClient(sb.address)},
                    retry_max=3, probe_interval_s=0.05,
                    request_timeout_s=10.0)
        with RouterServer(r, port=0) as srv:
            pa = _prompt_on(r, "a")
            ref = tiny_gpt.generate(
                paddle.to_tensor(np.asarray([pa], np.int32)),
                max_new_tokens=4).numpy()[0]
            code, out, _ = _http("POST", srv.address + "/generate",
                                 {"prompt": list(map(int, pa)),
                                  "max_new_tokens": 4}, timeout=60.0)
            assert code == 200 and out["replica"] == "a"
            assert out["ids"] == [int(x) for x in ref]
            # whole-fleet timeline before the kill: 3 sources, 3 pids
            tl = _load_timeline_tool()
            pairs = tl.router_sources(srv.address)
            assert [lbl for lbl, _ in pairs] == \
                ["router", "replica:a", "replica:b"]
            merged = tl.merge_traces(
                [tl.load_trace(src) for _, src in pairs],
                labels=[lbl for lbl, _ in pairs])
            assert {e["pid"] for e in merged["traceEvents"]} == \
                {0, 1, 2}
            # kill replica a's server: connection refused from now on
            sa.close()
            killed_a = True
            code, out, _ = _http("POST", srv.address + "/generate",
                                 {"prompt": list(map(int, pa)),
                                  "max_new_tokens": 4}, timeout=60.0)
            assert code == 200 and out["replica"] == "b"
            assert out["ids"] == [int(x) for x in ref]
            # the router learns of the death either way: traffic paid
            # a refused hop and failed over, or the background prober
            # got there first and the pick skipped the corpse
            assert out["attempts"] >= 2 or any(
                ev[0] == "probe" and ev[1] == "a"
                and ev[2] in (DEGRADED, DEAD)
                for ev in r.route_log())
    finally:
        if not killed_a:
            sa.close()
        sb.close()


# ---------------------------------------------------------------------------
# supervisor incarnations: breaker reset + stale-probe fencing
# ---------------------------------------------------------------------------

def _fake_engine():
    """The minimal engine surface InProcessReplica.probe() reads."""
    import types
    return types.SimpleNamespace(
        queue=types.SimpleNamespace(depth=lambda: 0),
        scheduler=types.SimpleNamespace(free_count=lambda: 4),
        num_slots=4)


def test_incarnation_bump_resets_breaker_and_history():
    """A replica respawned on the same URL (supervisor bumps the
    incarnation) must NOT inherit its dead predecessor's breaker: the
    successor's first probe swaps in a fresh CLOSED breaker and
    zeroes the health history, instead of walking OPEN -> HALF_OPEN
    -> trial like a same-process recovery would."""
    rep_client = InProcessReplica("a", _fake_engine())
    r = _router({"a": rep_client}, breaker_threshold=2)
    rep = r._reps()[0]
    r.probe_once()
    assert rep.incarnation == 0
    assert rep.signals["incarnation"] == 0
    # predecessor dies mid-traffic: breaker trips OPEN, probes fail
    old_breaker = rep.breaker
    old_breaker.record_failure()
    old_breaker.record_failure()
    assert old_breaker.state == OPEN
    rep_client.kill()
    r.probe_once()
    assert rep.probe_failures == 1
    # the supervisor respawns it: NEW incarnation on the old address
    rep_client.revive(bump_incarnation=True)
    r.probe_once()
    assert rep.incarnation == 1
    assert rep.breaker is not old_breaker      # atomic swap
    assert rep.breaker.state == CLOSED
    assert rep.probe_failures == 0
    assert rep.state == HEALTHY
    assert ("incarnation", "a", 1) in r.log
    # the reset is visible on every surface: registry view + gauge
    assert r.replicas()[0]["incarnation"] == 1
    g = r.registry.gauge("router.replica_incarnation.a", "")
    assert g.value == 1
    # a stale failure landing on the OLD breaker object (an in-flight
    # attempt that started before the respawn) cannot poison the
    # successor's fresh breaker
    old_breaker.record_failure()
    assert rep.breaker.state == CLOSED


def test_stale_probe_from_dead_incarnation_is_discarded():
    """The stale-probe race: a probe that left incarnation 0 before
    it died can arrive AFTER the registry already applied the
    successor's (incarnation 1) probe.  The whole stale body must be
    discarded — state, signals and breaker stay the successor's."""
    script = {"inc": 1}
    client = FakeReplica("a", health=lambda: {
        "queue_depth": 7 if script["inc"] == 0 else 0,
        "slots_free": 4,
        "draining": script["inc"] == 0,   # the corpse reported
        #   draining; applying it would stop routing to the successor
        "incarnation": script["inc"]})
    r = _router({"a": client})
    rep = r._reps()[0]
    r.probe_once()
    assert rep.incarnation == 1 and rep.state == HEALTHY
    # the delayed predecessor probe arrives late
    script["inc"] = 0
    out = r.probe_once()
    assert out["a"] == HEALTHY                # NOT draining
    assert rep.incarnation == 1
    assert rep.signals["queue_depth"] == 0    # stale signals dropped
    assert ("stale_probe", "a", 0) in r.log
    # same-incarnation probes keep applying normally
    script["inc"] = 1
    r.probe_once()
    assert rep.state == HEALTHY


def test_revive_without_bump_keeps_breaker_recovery_path():
    """Default revive() models the SAME process answering again: the
    incarnation does not advance and an OPEN breaker recovers through
    the probe-driven HALF_OPEN path, exactly as before supervisors
    existed."""
    client = InProcessReplica("a", _fake_engine())
    r = _router({"a": client}, breaker_threshold=1,
                breaker_cooldown_s=0.0)
    rep = r._reps()[0]
    r.probe_once()
    rep.breaker.record_failure()
    assert rep.breaker.state == OPEN
    client.kill()
    client.revive()
    old = rep.breaker
    r.probe_once()
    assert rep.incarnation == 0
    assert rep.breaker is old                  # no swap
    assert rep.breaker.state == HALF_OPEN      # cooled OPEN + probe
    assert not any(ev[0] == "incarnation" for ev in r.log)
