"""Sharded sparse tables (PS analogue).

Mirrors reference PS tests (fluid/distributed/test/brpc_service_sparse_
sgd_test.cc pull→push→pull cycle, table_test.cc, test_dist_fleet_ps*)
against the mesh-sharded implementation."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distributed as dist
from paddle_tpu.distributed import SparseTable, DistributedEmbedding, TheOnePS


@pytest.fixture()
def mesh():
    return dist.build_mesh(dp=4, sharding=2)


def test_pull_push_sgd_cycle(mesh):
    # reference: brpc_service_sparse_sgd_test.cc — pull, push grad, pull
    paddle.seed(0)
    t = SparseTable("emb", rows=16, dim=4, optimizer="sgd", lr=0.5,
                    mesh=mesh)
    ids = np.array([1, 3, 3], np.int32)
    before = t.pull(np.array([1, 3], np.int32)).numpy()
    grads = np.ones((3, 4), np.float32)
    t.push(ids, grads)
    after = t.pull(np.array([1, 3], np.int32)).numpy()
    # row 1 got one grad, row 3 accumulated two (SelectedRows merge-add)
    np.testing.assert_allclose(after[0], before[0] - 0.5 * 1.0, rtol=1e-5)
    np.testing.assert_allclose(after[1], before[1] - 0.5 * 2.0, rtol=1e-5)
    # untouched rows unchanged
    other = t.pull(np.array([0], np.int32)).numpy()
    t.push(np.array([1], np.int32), np.ones((1, 4), np.float32))
    np.testing.assert_array_equal(t.pull(np.array([0], np.int32)).numpy(),
                                  other)


def test_adam_rows_only_touched(mesh):
    paddle.seed(1)
    t = SparseTable("emb2", rows=8, dim=4, optimizer="adam", lr=0.1,
                    mesh=mesh)
    w0 = np.asarray(t.weight).copy()
    t.push(np.array([2], np.int32), np.ones((1, 4), np.float32))
    w1 = np.asarray(t.weight)
    assert not np.allclose(w1[2], w0[2])
    np.testing.assert_array_equal(w1[[0, 1, 3]], w0[[0, 1, 3]])
    # bias-corrected first adam step == lr regardless of grad scale
    np.testing.assert_allclose(w0[2] - w1[2], np.full(4, 0.1), rtol=1e-4)


def test_embedding_trains_regression(mesh):
    # learn target rows via repeated pull/push (async-PS-style loop)
    paddle.seed(2)
    t = SparseTable("emb3", rows=8, dim=2, optimizer="sgd", lr=0.3,
                    mesh=mesh)
    emb = DistributedEmbedding(t)
    ids = np.array([0, 1, 2, 3], np.int32)
    target = np.array([[1, 0], [0, 1], [1, 1], [-1, -1]], np.float32)
    losses = []
    for _ in range(60):
        out = emb(ids)
        diff = out.numpy() - target
        losses.append(float((diff ** 2).mean()))
        emb.apply_gradients(2 * diff / diff.size)
    assert losses[-1] < losses[0] * 0.01


def test_table_save_load_roundtrip(tmp_path, mesh):
    paddle.seed(3)
    runtime = TheOnePS()
    t = runtime.create_table("emb4", rows=8, dim=4, mesh=mesh)
    t.push(np.array([1, 2], np.int32), np.ones((2, 4), np.float32))
    ref = np.asarray(t.weight).copy()
    runtime.save_persistables(dirname=str(tmp_path))
    # fresh runtime warm-starts from the saved shards
    runtime2 = TheOnePS()
    runtime2.create_table("emb4", rows=8, dim=4, mesh=mesh)
    runtime2.init_server(dirname=str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(runtime2.tables["emb4"].weight), ref, rtol=1e-6)


def test_table_is_sharded_over_mesh(mesh):
    t = SparseTable("emb5", rows=16, dim=4, mesh=mesh)
    sh = t.weight.sharding
    assert sh.spec[0] == "sharding"  # row-sharded placement
