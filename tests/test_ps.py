"""Sharded sparse tables (PS analogue).

Mirrors reference PS tests (fluid/distributed/test/brpc_service_sparse_
sgd_test.cc pull→push→pull cycle, table_test.cc, test_dist_fleet_ps*)
against the mesh-sharded implementation."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distributed as dist
from paddle_tpu.distributed import SparseTable, DistributedEmbedding, TheOnePS


@pytest.fixture()
def mesh():
    return dist.build_mesh(dp=4, sharding=2)


def test_pull_push_sgd_cycle(mesh):
    # reference: brpc_service_sparse_sgd_test.cc — pull, push grad, pull
    paddle.seed(0)
    t = SparseTable("emb", rows=16, dim=4, optimizer="sgd", lr=0.5,
                    mesh=mesh)
    ids = np.array([1, 3, 3], np.int32)
    before = t.pull(np.array([1, 3], np.int32)).numpy()
    grads = np.ones((3, 4), np.float32)
    t.push(ids, grads)
    after = t.pull(np.array([1, 3], np.int32)).numpy()
    # row 1 got one grad, row 3 accumulated two (SelectedRows merge-add)
    np.testing.assert_allclose(after[0], before[0] - 0.5 * 1.0, rtol=1e-5)
    np.testing.assert_allclose(after[1], before[1] - 0.5 * 2.0, rtol=1e-5)
    # untouched rows unchanged
    other = t.pull(np.array([0], np.int32)).numpy()
    t.push(np.array([1], np.int32), np.ones((1, 4), np.float32))
    np.testing.assert_array_equal(t.pull(np.array([0], np.int32)).numpy(),
                                  other)


def test_adam_rows_only_touched(mesh):
    paddle.seed(1)
    t = SparseTable("emb2", rows=8, dim=4, optimizer="adam", lr=0.1,
                    mesh=mesh)
    w0 = np.asarray(t.weight).copy()
    t.push(np.array([2], np.int32), np.ones((1, 4), np.float32))
    w1 = np.asarray(t.weight)
    assert not np.allclose(w1[2], w0[2])
    np.testing.assert_array_equal(w1[[0, 1, 3]], w0[[0, 1, 3]])
    # bias-corrected first adam step == lr regardless of grad scale
    np.testing.assert_allclose(w0[2] - w1[2], np.full(4, 0.1), rtol=1e-4)


def test_embedding_trains_regression(mesh):
    # learn target rows via repeated pull/push (async-PS-style loop)
    paddle.seed(2)
    t = SparseTable("emb3", rows=8, dim=2, optimizer="sgd", lr=0.3,
                    mesh=mesh)
    emb = DistributedEmbedding(t)
    ids = np.array([0, 1, 2, 3], np.int32)
    target = np.array([[1, 0], [0, 1], [1, 1], [-1, -1]], np.float32)
    losses = []
    for _ in range(60):
        out = emb(ids)
        diff = out.numpy() - target
        losses.append(float((diff ** 2).mean()))
        emb.apply_gradients(2 * diff / diff.size)
    assert losses[-1] < losses[0] * 0.01


def test_table_save_load_roundtrip(tmp_path, mesh):
    paddle.seed(3)
    runtime = TheOnePS()
    t = runtime.create_table("emb4", rows=8, dim=4, mesh=mesh)
    t.push(np.array([1, 2], np.int32), np.ones((2, 4), np.float32))
    ref = np.asarray(t.weight).copy()
    runtime.save_persistables(dirname=str(tmp_path))
    # fresh runtime warm-starts from the saved shards
    runtime2 = TheOnePS()
    runtime2.create_table("emb4", rows=8, dim=4, mesh=mesh)
    runtime2.init_server(dirname=str(tmp_path))
    np.testing.assert_allclose(
        np.asarray(runtime2.tables["emb4"].weight), ref, rtol=1e-6)


def test_table_is_sharded_over_mesh(mesh):
    t = SparseTable("emb5", rows=16, dim=4, mesh=mesh)
    sh = t.weight.sharding
    assert sh.spec[0] == "sharding"  # row-sharded placement


def test_push_matches_numpy_adam_with_dups(mesh):
    """Dedup + segment-sum path vs a straight numpy reference with
    PER-ROW step counts (reference: per-row optimizer state in
    CommonSparseTable)."""
    paddle.seed(4)
    t = SparseTable("emb6", rows=12, dim=3, optimizer="adam", lr=0.05,
                    mesh=mesh)
    w = np.asarray(t.weight).copy()
    m = np.zeros_like(w); v = np.zeros_like(w)
    t_rows = np.zeros(12, np.int64)
    rs = np.random.RandomState(0)
    for _ in range(3):
        ids = rs.randint(0, 12, (6,)).astype(np.int32)
        g = rs.randn(6, 3).astype(np.float32)
        t.push(ids, g)
        merged = np.zeros_like(w)
        np.add.at(merged, ids, g)
        touched = np.zeros(12, bool); touched[ids] = True
        t_rows[touched] += 1
        b1, b2, eps = 0.9, 0.999, 1e-8
        m[touched] = b1 * m[touched] + (1 - b1) * merged[touched]
        v[touched] = b2 * v[touched] + (1 - b2) * merged[touched] ** 2
        bias1 = 1 - b1 ** t_rows[touched][:, None]
        bias2 = 1 - b2 ** t_rows[touched][:, None]
        w[touched] -= 0.05 * (m[touched] / bias1) / (
            np.sqrt(v[touched] / bias2) + eps)
        np.testing.assert_allclose(np.asarray(t.weight), w, rtol=2e-4,
                                   atol=1e-5)
    np.testing.assert_array_equal(np.asarray(t.state["t"]), t_rows)


def test_sharded_save_load_multiple_files(tmp_path, mesh):
    paddle.seed(5)
    t = SparseTable("emb7", rows=20, dim=4, optimizer="adam", lr=0.1,
                    mesh=mesh)
    t.push(np.arange(10, dtype=np.int32), np.ones((10, 4), np.float32))
    ref_w = np.asarray(t.weight).copy()
    ref_m = np.asarray(t.state["m"]).copy()
    t.save(str(tmp_path), num_shards=4)
    import os
    files = sorted(os.listdir(tmp_path))
    assert sum(f.startswith("emb7.shard") for f in files) == 4
    t2 = SparseTable("emb7", rows=20, dim=4, optimizer="adam", lr=0.1,
                     mesh=mesh)
    t2.load(str(tmp_path))
    np.testing.assert_allclose(np.asarray(t2.weight), ref_w, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(t2.state["m"]), ref_m, rtol=1e-6)
    tcounts = np.asarray(t2.state["t"])
    np.testing.assert_array_equal(tcounts[:10], 1)  # pushed rows
    np.testing.assert_array_equal(tcounts[10:], 0)


def test_push_cost_independent_of_table_size(mesh):
    """VERDICT #6 'done' criterion: push cost O(batch), not O(table).
    Compare wall time of a warmed push on a 200k-row vs 2k-row table —
    the round-1 dense-materialization implementation was ~100x apart."""
    import time
    paddle.seed(6)
    small = SparseTable("s", rows=2_000, dim=32, optimizer="adam", mesh=mesh)
    big = SparseTable("b", rows=200_000, dim=32, optimizer="adam", mesh=mesh)
    ids = np.random.RandomState(1).randint(0, 2_000, (128,)).astype(np.int32)
    g = np.ones((128, 32), np.float32)

    def timed(t):
        t.push(ids, g)  # warm/compile
        np.asarray(t.weight[0])
        t0 = time.perf_counter()
        for _ in range(20):
            t.push(ids, g)
        np.asarray(t.weight[0])
        return time.perf_counter() - t0

    ts, tb = timed(small), timed(big)
    assert tb < ts * 10, (ts, tb)


# ---------------------------------------------------------------------------
# HashedSparseTable: unbounded ids over a growing slab (round 4)

class TestHashedSparseTable:
    def test_unbounded_ids_and_growth(self, mesh):
        from paddle_tpu.distributed import HashedSparseTable
        paddle.seed(0)
        t = HashedSparseTable("h1", dim=4, initial_rows=4, optimizer="sgd",
                              lr=0.5, mesh=mesh)
        # ids far beyond any fixed capacity (feature hashes)
        ids = np.array([2**62 + 7, 123456789012345, 2**40, 17, 2**62 + 7],
                       np.int64)
        v1 = t.pull(ids).numpy()
        # same id -> same row
        np.testing.assert_allclose(v1[0], v1[4])
        assert t.size == 4
        # push 12 more distinct ids: slab must grow past initial_rows=4
        more = np.arange(100, 112, dtype=np.int64)
        t.pull(more)
        assert t.size == 16 and t.rows >= 16

    def test_push_updates_only_touched(self, mesh):
        from paddle_tpu.distributed import HashedSparseTable
        paddle.seed(1)
        t = HashedSparseTable("h2", dim=3, initial_rows=4, optimizer="sgd",
                              lr=1.0, mesh=mesh)
        ids = np.array([10**15, 5], np.int64)
        before = t.pull(ids).numpy()
        other = t.pull(np.array([777], np.int64)).numpy()
        g = np.ones((2, 3), np.float32)
        t.push(ids, g)
        after = t.pull(ids).numpy()
        np.testing.assert_allclose(after, before - 1.0, rtol=1e-6)
        np.testing.assert_allclose(
            t.pull(np.array([777], np.int64)).numpy(), other)

    def test_adam_matches_fixed_table(self, mesh):
        """Same pushes through hashed and fixed tables agree row-for-row."""
        from paddle_tpu.distributed import HashedSparseTable
        rs = np.random.RandomState(0)
        init = lambda shape, dtype: np.zeros(shape, dtype)
        t_fix = SparseTable("f3", rows=8, dim=3, optimizer="adam", lr=0.1,
                            initializer=init, mesh=mesh)
        t_h = HashedSparseTable("h3", dim=3, initial_rows=2,
                                optimizer="adam", lr=0.1,
                                initializer=init, mesh=mesh)
        big_ids = np.array([2**50, 3, 2**61, 40, 2**50], np.int64)
        fix_ids = np.array([0, 1, 2, 3, 0], np.int64)  # same collision map
        for _ in range(3):
            g = rs.rand(5, 3).astype(np.float32)
            t_fix.push(fix_ids, g)
            t_h.push(big_ids, g)
        np.testing.assert_allclose(
            t_h.pull(big_ids).numpy(), t_fix.pull(fix_ids).numpy(),
            rtol=1e-5)

    def test_shrink_evicts_stale(self, mesh):
        from paddle_tpu.distributed import HashedSparseTable
        paddle.seed(2)
        t = HashedSparseTable("h4", dim=2, initial_rows=4, optimizer="sgd",
                              lr=0.5, mesh=mesh)
        old = np.array([1, 2], np.int64)
        t.push(old, np.ones((2, 2), np.float32))
        for i in range(5):
            t.push(np.array([100 + i], np.int64),
                   np.ones((1, 2), np.float32))
        n = t.shrink(ttl=4)
        assert n == 2 and t.size == 5
        # evicted ids return as FRESH rows (slot reused, value reset)
        fresh = t.pull(old)
        assert np.isfinite(fresh.numpy()).all()

    def test_save_load_roundtrip(self, tmp_path, mesh):
        from paddle_tpu.distributed import HashedSparseTable
        paddle.seed(3)
        t = HashedSparseTable("h5", dim=3, initial_rows=2,
                              optimizer="adam", lr=0.1, mesh=mesh)
        ids = np.array([2**55, 9, 2**44, 123], np.int64)
        t.push(ids, np.ones((4, 3), np.float32))
        want = t.pull(ids).numpy()
        t.save(str(tmp_path))
        paddle.seed(4)  # different init must not matter after load
        t2 = HashedSparseTable("h5", dim=3, initial_rows=2,
                               optimizer="adam", lr=0.1, mesh=mesh)
        t2.load(str(tmp_path))
        np.testing.assert_allclose(t2.pull(ids).numpy(), want, rtol=1e-6)
        assert t2.size == 4

    def test_max_rows_exhaustion_raises(self, mesh):
        from paddle_tpu.distributed import HashedSparseTable
        t = HashedSparseTable("h6", dim=2, initial_rows=2, max_rows=4,
                              optimizer="sgd", mesh=mesh)
        with pytest.raises(RuntimeError, match="max_rows"):
            t.pull(np.arange(5, dtype=np.int64))

    def test_runtime_facade_creates_hashed(self, mesh):
        ps = TheOnePS()
        t = ps.create_table("h7", rows=None, dim=2, initial_rows=2,
                            mesh=mesh)
        from paddle_tpu.distributed import HashedSparseTable
        assert isinstance(t, HashedSparseTable)

    def test_pull_preserves_ids_shape(self, mesh):
        from paddle_tpu.distributed import HashedSparseTable
        t = HashedSparseTable("h8", dim=3, initial_rows=2, mesh=mesh)
        ids = np.array([[2**50, 5], [7, 2**50]], np.int64)
        out = t.pull(ids)
        assert list(out.shape) == [2, 2, 3]
        np.testing.assert_allclose(out.numpy()[0, 0], out.numpy()[1, 1])

    def test_clamped_growth_keeps_valid_sharding(self, mesh):
        from paddle_tpu.distributed import HashedSparseTable
        # shard axis is 2; max_rows=6 forces a non-divisible slab once
        t = HashedSparseTable("h9", dim=2, initial_rows=4, max_rows=6,
                              optimizer="sgd", mesh=mesh)
        t.pull(np.arange(6, dtype=np.int64))       # grows 4 -> 6
        assert t.rows == 6
        t.push(np.arange(6, dtype=np.int64), np.ones((6, 2), np.float32))
        assert np.isfinite(np.asarray(t.weight)).all()

    def test_load_into_default_capacity_table(self, tmp_path, mesh):
        """Saved slab/max_rows win over the fresh table's constructor
        args — no need to re-pass the original initial_rows/max_rows."""
        from paddle_tpu.distributed import HashedSparseTable
        t = HashedSparseTable("h10", dim=2, initial_rows=2, max_rows=6,
                              mesh=mesh)
        ids = np.arange(6, dtype=np.int64) + 2**33
        t.push(ids, np.ones((6, 2), np.float32))   # grows 2 -> 4 -> 6
        want = t.pull(ids).numpy()
        t.save(str(tmp_path))
        t2 = HashedSparseTable("h10", dim=2, mesh=mesh)  # defaults
        t2.load(str(tmp_path))
        assert t2.rows == 6 and t2.max_rows == 6
        np.testing.assert_allclose(t2.pull(ids).numpy(), want, rtol=1e-6)
