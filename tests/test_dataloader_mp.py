"""Process-based DataLoader workers + device prefetch.

Reference parity targets: fluid/dataloader/dataloader_iter.py:464
(multiprocess workers), mmap_allocator.cc (shared-memory transport),
buffered_reader.cc (async double buffer).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import io


class SquareDataset(io.Dataset):
    def __init__(self, n=64):
        self.n = n

    def __getitem__(self, i):
        return (np.full((3, 4), i, np.float32),
                np.asarray(i * i, np.int64))

    def __len__(self):
        return self.n


class FailingDataset(io.Dataset):
    def __getitem__(self, i):
        if i == 7:
            raise ValueError("boom at 7")
        return np.zeros((2,), np.float32)

    def __len__(self):
        return 16


class CountingIterable(io.IterableDataset):
    """Shards itself across workers via get_worker_info (reference
    worker.py WorkerInfo contract)."""

    def __init__(self, n=32):
        self.n = n

    def __iter__(self):
        info = io.get_worker_info()
        if info is None:
            ids = range(self.n)
        else:
            ids = range(info.id, self.n, info.num_workers)
        for i in ids:
            yield np.asarray([i], np.int64)


def _collect(loader):
    xs, ys = [], []
    for bx, by in loader:
        xs.append(bx.numpy())
        ys.append(by.numpy())
    return np.concatenate(xs), np.concatenate(ys)


class TestMultiprocessDataLoader:
    @pytest.mark.parametrize("use_shared_memory", [True, False])
    def test_ordered_and_complete(self, use_shared_memory):
        ds = SquareDataset(50)
        loader = io.DataLoader(ds, batch_size=8, num_workers=2,
                               use_shared_memory=use_shared_memory)
        xs, ys = _collect(loader)
        assert xs.shape == (50, 3, 4)
        np.testing.assert_array_equal(xs[:, 0, 0],
                                      np.arange(50, dtype=np.float32))
        np.testing.assert_array_equal(ys, np.arange(50) ** 2)

    def test_matches_single_process(self):
        ds = SquareDataset(33)
        a = _collect(io.DataLoader(ds, batch_size=5, num_workers=0))
        b = _collect(io.DataLoader(ds, batch_size=5, num_workers=3))
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(a[1], b[1])

    def test_worker_exception_propagates(self):
        loader = io.DataLoader(FailingDataset(), batch_size=4,
                               num_workers=2)
        with pytest.raises(RuntimeError, match="boom at 7"):
            for _ in loader:
                pass

    def test_persistent_workers_two_epochs(self):
        ds = SquareDataset(24)
        loader = io.DataLoader(ds, batch_size=6, num_workers=2,
                               persistent_workers=True)
        for _ in range(2):
            xs, ys = _collect(loader)
            np.testing.assert_array_equal(
                xs[:, 0, 0], np.arange(24, dtype=np.float32))
        assert loader._pool is not None and not loader._pool._closed
        procs = loader._pool.procs
        assert all(p.is_alive() for p in procs)
        loader._pool.close()

    def test_early_break_cleans_up(self):
        ds = SquareDataset(64)
        loader = io.DataLoader(ds, batch_size=4, num_workers=2)
        it = iter(loader)
        next(it)
        next(it)
        del it  # generator finalizer must close the pool
        assert loader._pool is None or loader._pool._closed

    def test_worker_init_fn_and_info(self):
        seen = []

        class ProbeDataset(io.Dataset):
            def __getitem__(self, i):
                info = io.get_worker_info()
                assert info is not None and 0 <= info.id < 2
                return np.asarray([info.id], np.int64)

            def __len__(self):
                return 8

        loader = io.DataLoader(ProbeDataset(), batch_size=2, num_workers=2,
                               worker_init_fn=lambda wid: seen.append(wid))
        ids = np.concatenate([b.numpy() for b in loader]).ravel()
        assert set(ids.tolist()) <= {0, 1}
        # worker_init_fn ran in the workers, not here
        assert seen == []

    def test_iterable_dataset_workers_cover_all(self):
        loader = io.DataLoader(CountingIterable(32), batch_size=4,
                               num_workers=2)
        got = sorted(
            int(v) for b in loader for v in np.asarray(b.numpy()).ravel())
        assert got == list(range(32))

    def test_get_worker_info_none_in_parent(self):
        assert io.get_worker_info() is None

    def test_nested_dict_batches(self):
        class DictDataset(io.Dataset):
            def __getitem__(self, i):
                return {"x": np.full((2,), i, np.float32),
                        "meta": {"idx": np.asarray(i, np.int64)}}

            def __len__(self):
                return 10

        loader = io.DataLoader(DictDataset(), batch_size=5, num_workers=2)
        out = list(loader)
        assert len(out) == 2
        np.testing.assert_array_equal(
            np.asarray(out[0]["meta"]["idx"].numpy()), np.arange(5))


class TestDeviceLoader:
    def test_device_prefetch_values(self):
        ds = SquareDataset(20)
        loader = io.DataLoader(ds, batch_size=5, num_workers=2)
        dev = io.DeviceLoader(loader, buffer_size=2)
        xs = np.concatenate([bx.numpy() for bx, _ in dev])
        np.testing.assert_array_equal(
            xs[:, 0, 0], np.arange(20, dtype=np.float32))

    def test_device_prefetch_sharded(self):
        import jax
        from paddle_tpu.distributed import mesh as mesh_mod
        mesh = mesh_mod.ensure_mesh()
        ds = SquareDataset(16)
        loader = io.DataLoader(ds, batch_size=8, num_workers=0)

        def sharding_fn(shape):
            from jax.sharding import NamedSharding
            return NamedSharding(
                mesh, mesh_mod.batch_partition_spec(shape, mesh))

        dev = io.DeviceLoader(loader, sharding_fn=sharding_fn, wrap=False)
        batches = list(dev)
        assert all(isinstance(b[0], jax.Array) for b in batches)

    def test_fit_uses_prefetcher(self):
        from paddle_tpu import nn
        from paddle_tpu.io import TensorDataset

        x = np.random.RandomState(0).randn(32, 4).astype("float32")
        y = (x.sum(1) > 0).astype("int64")
        ds = TensorDataset([x, y])
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.Adam(learning_rate=1e-2,
                                  parameters=net.parameters()),
            nn.CrossEntropyLoss())
        model.fit(ds, batch_size=8, epochs=2, verbose=0, num_workers=2)


class TestReviewRegressions:
    def test_concurrent_iterators_same_loader(self):
        ds = SquareDataset(12)
        loader = io.DataLoader(ds, batch_size=4, num_workers=2,
                               persistent_workers=True)
        outer = iter(loader)
        o1 = next(outer)
        inner_vals = [bx.numpy()[:, 0, 0] for bx, _ in loader]
        rest = [bx.numpy()[:, 0, 0] for bx, _ in outer]
        got_outer = np.concatenate([o1[0].numpy()[:, 0, 0]] + rest)
        np.testing.assert_array_equal(got_outer,
                                      np.arange(12, dtype=np.float32))
        np.testing.assert_array_equal(np.concatenate(inner_vals),
                                      np.arange(12, dtype=np.float32))
        if loader._pool is not None:
            loader._pool.close()

    def test_dead_worker_raises_not_hangs(self):
        import os as _os

        class KillerDataset(io.Dataset):
            def __getitem__(self, i):
                if i == 3:
                    _os._exit(1)  # simulate OOM-kill/segfault
                return np.zeros((2,), np.float32)

            def __len__(self):
                return 16

        loader = io.DataLoader(KillerDataset(), batch_size=2,
                               num_workers=1)
        with pytest.raises(RuntimeError, match="died"):
            for _ in loader:
                pass

    def test_stale_exception_does_not_kill_next_epoch(self):
        class LateFail(io.Dataset):
            def __getitem__(self, i):
                if i == 10:
                    raise ValueError("late boom")
                return np.zeros((2,), np.float32)

            def __len__(self):
                return 12

        loader = io.DataLoader(LateFail(), batch_size=2, num_workers=2,
                               persistent_workers=True)
        it = iter(loader)
        next(it)  # batch 0; batch with idx 10 may fail in-flight
        del it    # abandon epoch, stale exception may sit in result_q
        import time
        time.sleep(0.3)
        # next epoch over only-good indices must not see the stale error
        good = io.DataLoader(
            LateFail(), batch_sampler=io.BatchSampler(
                sampler=io.SequenceSampler(list(range(8))), batch_size=2),
        )
        # reuse the SAME pool: manual generation bump over the same loader
        from paddle_tpu.io.worker import MultiprocessMapIter
        batches = [[0, 1], [2, 3], [4, 5]]
        out = list(MultiprocessMapIter(loader, batches,
                                       loader._get_pool()))
        assert len(out) == 3
        loader._pool.close()

    @pytest.mark.slow
    def test_iterable_dead_worker_raises(self):
        import os as _os

        class KillerIterable(io.IterableDataset):
            def __iter__(self):
                info = io.get_worker_info()
                if info is not None and info.id == 0:
                    _os._exit(1)
                for i in range(4):
                    yield np.zeros((2,), np.float32)

        loader = io.DataLoader(KillerIterable(), batch_size=2,
                               num_workers=1)
        with pytest.raises(RuntimeError, match="died|dead"):
            for _ in loader:
                pass

    def test_fresh_pools_get_fresh_augmentation_seeds(self):
        class AugDataset(io.Dataset):
            def __getitem__(self, i):
                return np.random.rand(3).astype(np.float32)

            def __len__(self):
                return 4

        loader = io.DataLoader(AugDataset(), batch_size=4, num_workers=1)
        e1 = next(iter(loader)).numpy()
        e2 = next(iter(loader)).numpy()
        assert not np.allclose(e1, e2), "epochs replayed identical RNG"

    @pytest.mark.slow
    def test_iterable_early_finisher_not_flagged_dead(self):
        import time as _t

        class Uneven(io.IterableDataset):
            def __iter__(self):
                info = io.get_worker_info()
                if info.id == 0:
                    return iter(())  # finishes instantly
                for i in range(2):
                    _t.sleep(6)  # slower than the 5s poll slice
                    yield np.asarray([i], np.int64)

        loader = io.DataLoader(Uneven(), batch_size=1, num_workers=2,
                               use_shared_memory=False)
        got = [int(b.numpy().ravel()[0]) for b in loader]
        assert sorted(got) == [0, 1]


class TestPoolLifecycle:
    @pytest.mark.slow
    def test_abandoned_unstarted_iterator_releases_pool(self):
        """An iterator obtained but never advanced must release its
        claim on GC — previously pool.busy stayed True forever and each
        epoch leaked a fresh worker pool (advisor round-2 finding)."""
        import gc
        loader = io.DataLoader(SquareDataset(16), batch_size=4,
                               num_workers=2, persistent_workers=True)
        it = iter(loader)          # claims the pool, never started
        pool = loader._pool
        assert pool is not None and pool.busy
        del it
        gc.collect()
        assert not pool.busy       # released on GC
        # next epoch reuses the SAME pool — no leak
        n = sum(1 for _ in loader)
        assert n == 4
        assert loader._pool is pool
        assert len(loader._live_pools) == 1
        loader._pool.close()

    def test_abandoned_mid_iteration_releases_pool(self):
        import gc
        loader = io.DataLoader(SquareDataset(32), batch_size=4,
                               num_workers=2, persistent_workers=True)
        it = iter(loader)
        next(it)                   # started, then abandoned
        pool = loader._pool
        del it
        gc.collect()
        assert not pool.busy
        assert sum(1 for _ in loader) == 8
        loader._pool.close()

    def test_del_closes_every_spawned_pool(self):
        import gc
        loader = io.DataLoader(SquareDataset(16), batch_size=4,
                               num_workers=2, persistent_workers=True)
        it1 = iter(loader)
        it2 = iter(loader)         # concurrent: second pool
        pools = list(loader._live_pools)
        assert len(pools) == 2
        del it1, it2
        gc.collect()
        loader.__del__()
        assert all(p._closed for p in pools)

    def test_persistent_concurrent_pools_recycled(self):
        """With persistent_workers, the extra pool spawned for a second
        concurrent iterator must be REUSED by later epochs, not leak one
        pool per epoch (review finding)."""
        import gc
        loader = io.DataLoader(SquareDataset(16), batch_size=4,
                               num_workers=2, persistent_workers=True)
        for _ in range(3):
            it1, it2 = iter(loader), iter(loader)
            next(it1), next(it2)
            del it1, it2
            gc.collect()
        assert len(loader._live_pools) == 2, len(loader._live_pools)
        for p in list(loader._live_pools):
            p.close()
