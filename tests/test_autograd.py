import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import Tensor


def test_simple_backward():
    x = paddle_tpu.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_rule():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    y = paddle_tpu.exp(x)
    z = paddle_tpu.log(y) * 3.0
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0], rtol=1e-5)


def test_grad_accumulation_two_paths():
    x = paddle_tpu.to_tensor([2.0], stop_gradient=False)
    y = x * 3.0 + x * x  # dy/dx = 3 + 2x = 7
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


def test_backward_twice_accumulates_on_leaf():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    (x * 2.0).backward()
    (x * 3.0).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_no_grad_blocks_tape():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    with paddle_tpu.no_grad():
        y = x * 2.0
    assert y.stop_gradient
    assert y._grad_node is None


def test_stop_gradient_cuts_graph():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    y = x * 2.0
    yd = y.detach()
    z = yd * 3.0
    assert z.stop_gradient


def test_retain_graph_error_without():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    y = x * 2.0
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_retain_graph_allows_second_backward():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    y = x * 2.0
    y.backward(retain_graph=True)
    # reconnect root for a second pass
    x.clear_grad()


def test_non_scalar_backward_needs_grad():
    x = paddle_tpu.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 2.0
    with pytest.raises(RuntimeError):
        y.backward()
    y2 = x * 2.0
    y2.backward(paddle_tpu.ones([2]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_matmul_grad():
    a = paddle_tpu.to_tensor(np.random.rand(3, 4).astype(np.float32),
                             stop_gradient=False)
    b = paddle_tpu.to_tensor(np.random.rand(4, 5).astype(np.float32),
                             stop_gradient=False)
    out = paddle_tpu.matmul(a, b)
    out.sum().backward()
    np.testing.assert_allclose(
        a.grad.numpy(), np.ones((3, 5)) @ b.numpy().T, rtol=1e-5)
    np.testing.assert_allclose(
        b.grad.numpy(), a.numpy().T @ np.ones((3, 5)), rtol=1e-5)


def test_broadcast_grad():
    a = paddle_tpu.to_tensor(np.ones((3, 4), np.float32),
                             stop_gradient=False)
    b = paddle_tpu.to_tensor(np.ones((4,), np.float32),
                             stop_gradient=False)
    (a + b).sum().backward()
    np.testing.assert_allclose(b.grad.numpy(), [3.0] * 4)


def test_multi_output_split_grad():
    x = paddle_tpu.to_tensor(np.arange(6, dtype=np.float32),
                             stop_gradient=False)
    a, b = paddle_tpu.split(x, 2)
    (a.sum() * 2 + b.sum() * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2, 2, 3, 3, 3])


def test_unused_output_gets_zero_grad():
    x = paddle_tpu.to_tensor(np.arange(6, dtype=np.float32),
                             stop_gradient=False)
    a, b = paddle_tpu.split(x, 2)
    a.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [1, 1, 1, 0, 0, 0])


def test_paddle_grad_api():
    x = paddle_tpu.to_tensor([2.0], stop_gradient=False)
    y = x * x
    (g,) = paddle_tpu.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [4.0])
    assert x.grad is None  # paddle.grad must not pollute .grad


def test_retain_grads_intermediate():
    x = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    y = x * 2.0
    y.retain_grads()
    z = y * 3.0
    z.backward()
    np.testing.assert_allclose(y.grad.numpy(), [3.0])


def test_getitem_grad():
    x = paddle_tpu.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                             stop_gradient=False)
    y = x[0]
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[1, 1, 1], [0, 0, 0]])
