"""paddle.fluid compatibility namespace: 1.x-era scripts run unchanged
(reference: python/paddle/fluid/__init__.py surface)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import optimizer


def test_fluid_static_one_x_style():
    paddle.enable_static()
    main = fluid.Program()
    try:
        with fluid.program_guard(main):
            x = fluid.data("x", [8, 4])
            y = fluid.data("y", [8, 1])
            h = fluid.layers.fc(x, 16, activation="relu")
            pred = fluid.layers.fc(h, 1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            optimizer.SGD(learning_rate=0.1).minimize(loss)
            exe = fluid.Executor(fluid.CPUPlace())
            rng = np.random.RandomState(0)
            xv = rng.rand(8, 4).astype("float32")
            yv = rng.rand(8, 1).astype("float32")
            l0 = exe.run(main, feed={"x": xv, "y": yv},
                         fetch_list=[loss])[0]
            for _ in range(30):
                l1 = exe.run(main, feed={"x": xv, "y": yv},
                             fetch_list=[loss])[0]
        assert float(l1) < float(l0)
    finally:
        paddle.disable_static()


def test_fluid_dygraph_guard():
    with fluid.dygraph.guard():
        net = fluid.dygraph.Linear(4, 2)
        v = fluid.dygraph.to_variable(np.ones((3, 4), "float32"))
        out = net(v)
        assert out.shape == [3, 2]
        assert fluid.dygraph.enabled()


def test_fluid_dygraph_pool2d_and_containers():
    with fluid.dygraph.guard():
        pool = fluid.dygraph.Pool2D(pool_size=2, pool_type="avg",
                                    pool_stride=2)
        x = fluid.dygraph.to_variable(np.ones((1, 1, 4, 4), "float32"))
        assert pool(x).shape == [1, 1, 2, 2]
        seq = fluid.dygraph.Sequential(fluid.dygraph.Linear(4, 8),
                                       fluid.dygraph.Linear(8, 2))
        assert seq(fluid.dygraph.to_variable(
            np.ones((2, 4), "float32"))).shape == [2, 2]


def test_fluid_layers_ops_eager():
    a = paddle.to_tensor(np.array([[1.0, 2.0]], "float32"))
    b = paddle.to_tensor(np.array([[3.0], [4.0]], "float32"))
    out = fluid.layers.matmul(a, b)
    assert float(out.numpy()) == pytest.approx(11.0)
    s = fluid.layers.reduce_sum(fluid.layers.elementwise_add(a, a))
    assert float(s.numpy()) == pytest.approx(6.0)
    arr = fluid.layers.create_array()
    fluid.layers.array_write(a, 0, arr)
    assert int(fluid.layers.array_length(arr).numpy()) == 1


def test_fluid_layers_data_rejects_appended_batch():
    paddle.enable_static()
    try:
        with pytest.raises(ValueError):
            fluid.layers.data("x", [4], append_batch_size=True)
    finally:
        paddle.disable_static()


def test_version_module():
    from paddle_tpu import version
    assert version.full_version == paddle.__version__
    version.show()


# ---- regressions from code review ----------------------------------------

def test_fluid_mul_num_col_dims():
    # 1.x mul flattens x after x_num_col_dims (reference mul_op.cc)
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 4).astype("float32")
    y = rng.rand(12, 5).astype("float32")
    out = fluid.layers.mul(paddle.to_tensor(x), paddle.to_tensor(y),
                           x_num_col_dims=1)
    np.testing.assert_allclose(out.numpy(),
                               x.reshape(2, 12) @ y, rtol=1e-5)
    out2 = fluid.layers.mul(paddle.to_tensor(x), paddle.to_tensor(y.reshape(4, 3, 5)),
                            x_num_col_dims=2, y_num_col_dims=1)
    assert out2.shape == [2, 3, 3, 5]


def test_pool2d_exclusive_divisor():
    # exclusive=False includes padding in the average divisor
    x = paddle.to_tensor(np.ones((1, 1, 2, 2), "float32"))
    from paddle_tpu.nn.functional import pool2d
    incl = pool2d(x, pool_size=2, pool_type="avg", pool_stride=1,
                  pool_padding=1, exclusive=False)
    excl = pool2d(x, pool_size=2, pool_type="avg", pool_stride=1,
                  pool_padding=1, exclusive=True)
    # corner: 1 valid cell of 4 -> 0.25 vs 1.0
    assert float(incl.numpy()[0, 0, 0, 0]) == pytest.approx(0.25)
    assert float(excl.numpy()[0, 0, 0, 0]) == pytest.approx(1.0)


def test_train_step_accepts_device_arrays():
    import jax.numpy as jnp
    from paddle_tpu.parallel.train_step import TrainStep
    from paddle_tpu import nn, optimizer, distributed as dist

    class MSE(nn.Layer):
        def forward(self, p, l):
            return paddle.mean((p - l) ** 2)

    paddle.seed(0)
    net = nn.Linear(4, 1)
    step = TrainStep(net, optimizer.SGD(learning_rate=0.1,
                                        parameters=net.parameters()),
                     loss_fn=MSE(), mesh=dist.build_mesh(dp=8))
    x = jnp.ones((8, 4))     # raw device arrays, not Tensors
    y = jnp.zeros((8, 1))
    l0 = float(step.step([x], [y]).numpy())
    l1 = float(step.step([x], [y]).numpy())
    assert l1 < l0


class TestFluidSubmodules:
    def test_nets_simple_img_conv_pool(self):
        import paddle_tpu as paddle
        import paddle_tpu.fluid as fluid
        import numpy as np
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                img = fluid.layers.data("img", [2, 1, 8, 8],
                                        append_batch_size=False)
                out = fluid.nets.simple_img_conv_pool(
                    img, num_filters=4, filter_size=3, pool_size=2,
                    pool_stride=2, act="relu")
            exe = fluid.Executor()
            exe.run(startup)
            (res,) = exe.run(
                main,
                feed={"img": np.random.RandomState(0).randn(
                    2, 1, 8, 8).astype("float32")},
                fetch_list=[out])
            assert np.asarray(res).shape == (2, 4, 3, 3)
        finally:
            paddle.disable_static()

    def test_nets_glu_and_attention(self):
        import paddle_tpu as paddle
        import paddle_tpu.fluid as fluid
        import numpy as np
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 6).astype("float32"))
        out = fluid.nets.glu(x, dim=-1)
        assert out.shape == [2, 3]
        q = paddle.to_tensor(
            np.random.RandomState(2).randn(2, 5, 8).astype("float32"))
        att = fluid.nets.scaled_dot_product_attention(q, q, q, num_heads=2)
        assert att.shape == [2, 5, 8]

    def test_average(self):
        import paddle_tpu.fluid as fluid
        wa = fluid.average.WeightedAverage()
        wa.add(2.0, weight=1)
        wa.add(4.0, weight=3)
        assert abs(wa.eval() - 3.5) < 1e-6

    def test_backward_module(self):
        import paddle_tpu.fluid as fluid
        assert callable(fluid.backward.append_backward)
        assert callable(fluid.backward.gradients)

    def test_unique_name(self):
        import paddle_tpu.fluid as fluid
        a = fluid.unique_name.generate("w")
        b = fluid.unique_name.generate("w")
        assert a != b

    def test_transpiler_sync_shim_async_guided(self):
        # round 5: sync transpile WORKS (shim); async still guides
        import os
        import paddle_tpu.fluid as fluid
        import pytest
        t = fluid.transpiler.DistributeTranspiler()
        paddle.enable_static()
        try:
            t.transpile(0, pservers="127.0.0.1:6170", trainers=1)
            assert t.get_trainer_program() is not None
            with pytest.raises(NotImplementedError,
                               match="GeoSparseTable"):
                t.transpile(0, sync_mode=False)
        finally:
            paddle.disable_static()
            for k in ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM"):
                os.environ.pop(k, None)

    def test_deprecated_modules_error(self):
        import paddle_tpu.fluid as fluid
        import pytest
        with pytest.raises(NotImplementedError, match="paddle.metric"):
            fluid.evaluator.ChunkEvaluator
