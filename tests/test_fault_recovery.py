"""Job-level fault recovery end-to-end (§5.3): a training process is
SIGKILL-analogue-murdered mid-job, relaunched, resumes from the last
auto-checkpoint, and finishes with EXACTLY the weights of an
uninterrupted run (reference: incubate auto_checkpoint's
train_epoch_range contract)."""
import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

WORKER = os.path.join(os.path.dirname(__file__),
                      "fault_recovery_worker.py")


def _run(tmp, ckpt_name, out_name, kill_after=-1):
    env = dict(os.environ,
               PADDLE_TPU_PLATFORM="cpu",
               PADDLE_RUNNING_ENV="PADDLE_EDL_AUTO_CHECKPOINT",
               PADDLE_CHECKPOINT_DIR=str(tmp / ckpt_name),
               OUT_PATH=str(tmp / out_name),
               KILL_AFTER_EPOCH=str(kill_after))
    return subprocess.run([sys.executable, WORKER], env=env,
                          capture_output=True, text=True, timeout=300)


def test_kill_and_resume_matches_clean_run(tmp_path):
    # clean reference run
    clean = _run(tmp_path, "ck_clean", "clean.npz")
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert "DONE" in clean.stdout

    # killed mid-job (dies before epoch 3's snapshot lands)
    killed = _run(tmp_path, "ck_fault", "fault.npz", kill_after=3)
    assert killed.returncode == 137
    assert "EPOCH 3" in killed.stdout
    assert not (tmp_path / "fault.npz").exists()

    # relaunch: resumes at epoch 3 (last snapshot = epoch 2), finishes
    resumed = _run(tmp_path, "ck_fault", "fault.npz")
    assert resumed.returncode == 0, resumed.stdout + resumed.stderr
    first_line = resumed.stdout.splitlines()[0]
    assert first_line.startswith("EPOCH 3"), resumed.stdout

    a = np.load(tmp_path / "clean.npz")
    b = np.load(tmp_path / "fault.npz")
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-6, atol=1e-7,
                                   err_msg=k)
