"""Inference golden-model parity (round 5, VERDICT r4 #7): the
reference's analyzer-tester pattern
(/root/reference/paddle/fluid/inference/tests/api — export a real
model, reload through the predictor, assert golden outputs): ResNet-50,
GPT-2 (tiny config, same code path as 345M), and an int8
(convert_to_int8) artifact, each vs the eager forward."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, nn, static
from paddle_tpu.inference import Config, create_predictor

slow = pytest.mark.slow


@slow
def test_resnet50_golden(tmp_path):
    from paddle_tpu.vision.models import resnet50
    paddle.seed(0)
    net = resnet50(num_classes=10)
    net.eval()
    x = np.random.RandomState(0).rand(2, 3, 64, 64).astype("float32")
    golden = net(paddle.to_tensor(x)).numpy()
    prefix = str(tmp_path / "r50")
    paddle.jit.save(net, prefix,
                    input_spec=[static.InputSpec([2, 3, 64, 64],
                                                 "float32", "image")])
    pred = create_predictor(Config(prefix))
    out, = pred.run([x])
    np.testing.assert_allclose(out, golden, rtol=1e-4, atol=1e-4)
    # classification decisions identical, not just close
    assert (out.argmax(-1) == golden.argmax(-1)).all()


@slow
def test_gpt2_golden(tmp_path):
    from paddle_tpu.models import GPTModel
    paddle.seed(1)
    model = GPTModel.from_config("tiny")
    model.eval()
    ids = np.random.RandomState(1).randint(
        0, 128, (2, 32)).astype("int32")
    golden = model(paddle.to_tensor(ids)).numpy()
    prefix = str(tmp_path / "gpt2")
    paddle.jit.save(model, prefix,
                    input_spec=[static.InputSpec([2, 32], "int32",
                                                 "ids")])
    pred = create_predictor(Config(prefix))
    out, = pred.run([ids])
    np.testing.assert_allclose(out, golden, rtol=1e-4, atol=1e-4)
    assert (out.argmax(-1) == golden.argmax(-1)).all()


@slow
def test_int8_artifact_golden(tmp_path):
    """PTQ -> convert_to_int8 -> export -> Predictor: the reloaded
    artifact reproduces the live int8 model and stays within the
    documented tolerance of the float path."""
    from paddle_tpu.quantization import (PostTrainingQuantization,
                                         convert_to_int8)
    paddle.seed(2)
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    rs = np.random.RandomState(2)
    data = [paddle.to_tensor(rs.rand(4, 8).astype("float32"))
            for _ in range(4)]
    float_golden = None
    net.eval()
    x = rs.rand(4, 8).astype("float32")
    float_golden = net(paddle.to_tensor(x)).numpy()
    PostTrainingQuantization(net, data_loader=data).quantize()
    convert_to_int8(net)
    net.eval()
    int8_golden = net(paddle.to_tensor(x)).numpy()
    prefix = str(tmp_path / "int8")
    paddle.jit.save(net, prefix,
                    input_spec=[static.InputSpec([4, 8], "float32",
                                                 "x")])
    pred = create_predictor(Config(prefix))
    out, = pred.run([x])
    # artifact == live int8 model (exact: same compiled graph)
    np.testing.assert_allclose(out, int8_golden, rtol=1e-5, atol=1e-6)
    # and int8 tracks the float model within quantization tolerance
    np.testing.assert_allclose(out, float_golden, rtol=0.1, atol=0.1)
