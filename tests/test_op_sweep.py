"""Broad op sweep: numpy-reference forward + finite-difference gradient
checks over the op library (reference: the per-op OpTest files under
fluid/tests/unittests/ — op-vs-numpy with numeric grad is §4's core
pattern)."""
import numpy as np
import pytest

import paddle_tpu
from op_test import check_forward, check_grad

R = np.random.RandomState(7)


UNARY = [
    ("expm1", np.expm1, 0.1 + R.rand(3, 4)),
    ("log2", np.log2, 0.5 + R.rand(3, 4)),
    ("log10", np.log10, 0.5 + R.rand(3, 4)),
    ("log1p", np.log1p, R.rand(3, 4)),
    ("asin", np.arcsin, R.rand(3, 4) * 0.9),
    ("acos", np.arccos, R.rand(3, 4) * 0.9),
    ("atan", np.arctan, R.randn(3, 4)),
    ("sinh", np.sinh, R.randn(3, 4) * 0.5),
    ("cosh", np.cosh, R.randn(3, 4) * 0.5),
    ("asinh", np.arcsinh, R.randn(3, 4)),
    ("acosh", np.arccosh, 1.5 + R.rand(3, 4)),
    ("atanh", np.arctanh, R.rand(3, 4) * 0.8),
    ("reciprocal", np.reciprocal, 0.5 + R.rand(3, 4)),
    ("rsqrt", lambda a: 1 / np.sqrt(a), 0.5 + R.rand(3, 4)),
    ("sign", np.sign, R.randn(3, 4)),
    ("trunc", np.trunc, R.randn(3, 4) * 3),
    ("frac", lambda a: a - np.trunc(a), R.randn(3, 4) * 3),
    ("angle", np.angle, R.randn(3, 4)),
    ("erfinv", None, R.rand(3, 4) * 0.9),  # checked via erf roundtrip
]


class TestUnarySweep:
    @pytest.mark.parametrize("name,np_fn,x", UNARY,
                             ids=[u[0] for u in UNARY])
    def test_forward(self, name, np_fn, x):
        x = x.astype("float32")
        if np_fn is None:
            if name == "erfinv":
                out = paddle_tpu.erfinv(paddle_tpu.to_tensor(x))
                back = paddle_tpu.erf(out)
                np.testing.assert_allclose(back.numpy(), x, rtol=1e-4,
                                           atol=1e-5)
            return
        check_forward(getattr(paddle_tpu, name), np_fn, [x], rtol=1e-4,
                      atol=1e-5)

    @pytest.mark.parametrize("name", ["expm1", "log1p", "atan", "sinh",
                                      "asinh", "reciprocal", "rsqrt"])
    def test_grad(self, name):
        x = (0.5 + R.rand(3, 3)).astype("float32")
        check_grad(getattr(paddle_tpu, name), [x])


class TestBinarySweep:
    @pytest.mark.parametrize("name,np_fn", [
        ("atan2", np.arctan2),
        ("fmax", np.fmax),
        ("fmin", np.fmin),
        ("hypot", np.hypot),
        ("remainder", np.remainder),
        ("floor_divide", np.floor_divide),
        ("logical_xor", np.logical_xor),
    ])
    def test_forward(self, name, np_fn):
        x = (R.rand(4, 4) * 4 + 0.5).astype("float32")
        y = (R.rand(4, 4) * 4 + 0.5).astype("float32")
        check_forward(getattr(paddle_tpu, name), np_fn, [x, y], rtol=1e-5)

    def test_lerp(self):
        x = R.rand(3, 3).astype("float32")
        y = R.rand(3, 3).astype("float32")
        out = paddle_tpu.lerp(paddle_tpu.to_tensor(x),
                              paddle_tpu.to_tensor(y), 0.3)
        np.testing.assert_allclose(out.numpy(), x + 0.3 * (y - x),
                                   rtol=1e-5)

    def test_inner_outer(self):
        a = R.rand(3, 4).astype("float32")
        b = R.rand(5, 4).astype("float32")
        np.testing.assert_allclose(
            paddle_tpu.inner(paddle_tpu.to_tensor(a),
                             paddle_tpu.to_tensor(b)).numpy(),
            np.inner(a, b), rtol=1e-5)
        v1 = R.rand(3).astype("float32")
        v2 = R.rand(4).astype("float32")
        np.testing.assert_allclose(
            paddle_tpu.outer(paddle_tpu.to_tensor(v1),
                             paddle_tpu.to_tensor(v2)).numpy(),
            np.outer(v1, v2), rtol=1e-5)


class TestReductionSweep:
    @pytest.mark.parametrize("name,np_fn", [
        ("nansum", np.nansum),
        ("amax", np.max),
        ("amin", np.min),
        ("median", np.median),
    ])
    def test_forward(self, name, np_fn):
        x = R.rand(4, 6).astype("float32")
        check_forward(getattr(paddle_tpu, name), np_fn, [x], rtol=1e-5)

    def test_quantile(self):
        x = R.rand(64).astype("float32")
        out = paddle_tpu.quantile(paddle_tpu.to_tensor(x), 0.25)
        np.testing.assert_allclose(out.numpy(), np.quantile(x, 0.25),
                                   rtol=1e-4)

    def test_kthvalue_mode(self):
        x = R.rand(4, 9).astype("float32")
        v, idx = paddle_tpu.kthvalue(paddle_tpu.to_tensor(x), 3, axis=1)
        np.testing.assert_allclose(v.numpy(), np.sort(x, 1)[:, 2],
                                   rtol=1e-6)


class TestManipSweep:
    def test_roll_flip_rot90(self):
        x = R.rand(3, 4).astype("float32")
        t = paddle_tpu.to_tensor(x)
        np.testing.assert_array_equal(
            paddle_tpu.roll(t, 1, axis=0).numpy(), np.roll(x, 1, 0))
        np.testing.assert_array_equal(
            paddle_tpu.flip(t, axis=[1]).numpy(), np.flip(x, 1))
        np.testing.assert_array_equal(
            paddle_tpu.rot90(t).numpy(), np.rot90(x))

    def test_diff_cumprod(self):
        x = R.rand(3, 5).astype("float32")
        t = paddle_tpu.to_tensor(x)
        np.testing.assert_allclose(paddle_tpu.diff(t).numpy(),
                                   np.diff(x), rtol=1e-6)
        np.testing.assert_allclose(
            paddle_tpu.cumprod(t, dim=1).numpy(),
            np.cumprod(x, 1), rtol=1e-5)

    def test_searchsorted_bucketize(self):
        edges = np.array([0.2, 0.5, 0.8], "float32")
        x = R.rand(10).astype("float32")
        out = paddle_tpu.searchsorted(paddle_tpu.to_tensor(edges),
                                      paddle_tpu.to_tensor(x))
        np.testing.assert_array_equal(out.numpy(),
                                      np.searchsorted(edges, x))

    def test_repeat_interleave_moveaxis(self):
        x = R.rand(2, 3).astype("float32")
        t = paddle_tpu.to_tensor(x)
        np.testing.assert_array_equal(
            paddle_tpu.repeat_interleave(t, 2, axis=0).numpy(),
            np.repeat(x, 2, 0))
        y = R.rand(2, 3, 4).astype("float32")
        np.testing.assert_array_equal(
            paddle_tpu.moveaxis(paddle_tpu.to_tensor(y), 0, 2).numpy(),
            np.moveaxis(y, 0, 2))

    def test_take_along_put_along(self):
        x = R.rand(3, 4).astype("float32")
        idx = R.randint(0, 4, (3, 2))
        got = paddle_tpu.take_along_axis(
            paddle_tpu.to_tensor(x), paddle_tpu.to_tensor(idx), 1)
        np.testing.assert_allclose(got.numpy(),
                                   np.take_along_axis(x, idx, 1))

    def test_masked_select_nonzero(self):
        x = np.array([[1.0, -2.0], [3.0, -4.0]], "float32")
        t = paddle_tpu.to_tensor(x)
        got = paddle_tpu.masked_select(t, t > 0)
        np.testing.assert_array_equal(got.numpy(), [1.0, 3.0])
        nz = paddle_tpu.nonzero(t > 0)
        np.testing.assert_array_equal(nz.numpy(), [[0, 0], [1, 0]])


class TestLinalgSweep:
    def test_svd_reconstruction(self):
        x = R.rand(4, 3).astype("float32")
        u, s, vh = paddle_tpu.linalg.svd(paddle_tpu.to_tensor(x))
        rec = u.numpy() @ np.diag(s.numpy()) @ vh.numpy()
        np.testing.assert_allclose(rec, x, rtol=1e-4, atol=1e-5)

    def test_qr_reconstruction(self):
        x = R.rand(4, 4).astype("float32")
        q, r = paddle_tpu.linalg.qr(paddle_tpu.to_tensor(x))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), x, rtol=1e-4,
                                   atol=1e-5)

    def test_eigh_property(self):
        a = R.rand(4, 4).astype("float32")
        a = a + a.T
        w, v = paddle_tpu.linalg.eigh(paddle_tpu.to_tensor(a))
        np.testing.assert_allclose(
            v.numpy() @ np.diag(w.numpy()) @ v.numpy().T, a, rtol=1e-3,
            atol=1e-4)

    def test_det_slogdet_inverse(self):
        a = (np.eye(3) * 2 + R.rand(3, 3) * 0.1).astype("float32")
        t = paddle_tpu.to_tensor(a)
        np.testing.assert_allclose(paddle_tpu.linalg.det(t).numpy(),
                                   np.linalg.det(a), rtol=1e-4)
        np.testing.assert_allclose(
            paddle_tpu.linalg.inv(t).numpy(), np.linalg.inv(a),
            rtol=1e-3, atol=1e-4)

    def test_solve_lstsq(self):
        a = (np.eye(3) + R.rand(3, 3) * 0.2).astype("float32")
        b = R.rand(3, 2).astype("float32")
        got = paddle_tpu.linalg.solve(paddle_tpu.to_tensor(a),
                                      paddle_tpu.to_tensor(b))
        np.testing.assert_allclose(got.numpy(), np.linalg.solve(a, b),
                                   rtol=1e-3, atol=1e-4)

    def test_pinv_matrix_power(self):
        a = R.rand(3, 3).astype("float32")
        np.testing.assert_allclose(
            paddle_tpu.linalg.matrix_power(paddle_tpu.to_tensor(a),
                                           3).numpy(),
            np.linalg.matrix_power(a, 3), rtol=1e-3, atol=1e-4)


class TestNNFunctionalSweep:
    def test_softmax_grad(self):
        import paddle_tpu.nn.functional as F
        x = R.rand(3, 5).astype("float32")
        check_grad(lambda t: F.softmax(t), [x])

    def test_gelu_tanh_variants(self):
        import paddle_tpu.nn.functional as F
        x = R.randn(4, 4).astype("float32")
        ref = 0.5 * x * (1 + np.vectorize(np.math.erf if hasattr(
            np, "math") else __import__("math").erf)(x / np.sqrt(2)))
        got = F.gelu(paddle_tpu.to_tensor(x))
        np.testing.assert_allclose(got.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_log_softmax_matches_manual(self):
        import paddle_tpu.nn.functional as F
        x = R.rand(3, 5).astype("float32")
        got = F.log_softmax(paddle_tpu.to_tensor(x), axis=-1)
        ref = x - x.max(-1, keepdims=True)
        ref = ref - np.log(np.exp(ref).sum(-1, keepdims=True))
        np.testing.assert_allclose(got.numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_pad_modes(self):
        import paddle_tpu.nn.functional as F
        x = R.rand(1, 1, 4, 4).astype("float32")
        out = F.pad(paddle_tpu.to_tensor(x), [1, 1, 1, 1],
                    mode="reflect")
        ref = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)), "reflect")
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)

    def test_unfold_matches_manual(self):
        import paddle_tpu.nn.functional as F
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
        out = F.unfold(paddle_tpu.to_tensor(x), kernel_sizes=2)
        assert out.shape == [1, 4, 9]


class TestNNGradSweep:
    """Finite-difference grad checks for the structured nn ops
    (reference: per-op OpTest check_grad)."""

    def test_conv2d_grad(self):
        import paddle_tpu.nn.functional as F
        x = R.rand(2, 2, 6, 6).astype("float32")
        w = R.rand(3, 2, 3, 3).astype("float32")
        check_grad(lambda a, b: F.conv2d(a, b, padding=1), [x, w],
                   wrt=0, rtol=2e-2, atol=2e-3)
        check_grad(lambda a, b: F.conv2d(a, b, padding=1), [x, w],
                   wrt=1, rtol=2e-2, atol=2e-3)

    def test_avg_pool_grad(self):
        import paddle_tpu.nn.functional as F
        x = R.rand(1, 2, 4, 4).astype("float32")
        check_grad(lambda a: F.avg_pool2d(a, kernel_size=2), [x],
                   rtol=2e-2, atol=2e-3)

    def test_max_pool_grad(self):
        import paddle_tpu.nn.functional as F
        # distinct values so the argmax is stable under the fd delta
        x = (np.arange(32, dtype="float32").reshape(1, 2, 4, 4) * 0.37
             + R.rand(1, 2, 4, 4) * 1e-3)
        check_grad(lambda a: F.max_pool2d(a, kernel_size=2), [x],
                   rtol=2e-2, atol=2e-3)

    def test_layer_norm_grad(self):
        import paddle_tpu.nn.functional as F
        x = R.rand(3, 8).astype("float32")
        check_grad(lambda a: F.layer_norm(a, [8]), [x], rtol=2e-2,
                   atol=2e-3)

    def test_embedding_grad_scatters(self):
        import paddle_tpu.nn.functional as F
        import paddle_tpu
        w = paddle_tpu.to_tensor(R.rand(6, 4).astype("float32"),
                                 stop_gradient=False)
        ids = paddle_tpu.to_tensor(np.array([1, 1, 3], "int64"))
        out = F.embedding(ids, w)
        out.sum().backward()
        g = w.grad.numpy()
        np.testing.assert_allclose(g[1], 2.0)   # row hit twice
        np.testing.assert_allclose(g[3], 1.0)
        np.testing.assert_allclose(g[0], 0.0)

    def test_softmax_ce_grad(self):
        import paddle_tpu.nn.functional as F
        import paddle_tpu
        logits = R.rand(4, 5).astype("float32")
        labels = np.array([0, 2, 1, 4], "int64")
        t = paddle_tpu.to_tensor(logits, stop_gradient=False)
        loss = F.cross_entropy(t, paddle_tpu.to_tensor(labels))
        loss.backward()
        # analytic: (softmax - onehot) / batch
        p = np.exp(logits - logits.max(1, keepdims=True))
        p = p / p.sum(1, keepdims=True)
        onehot = np.eye(5)[labels]
        np.testing.assert_allclose(t.grad.numpy(), (p - onehot) / 4,
                                   rtol=1e-4, atol=1e-5)
