"""Test env: force CPU backend with 8 virtual devices BEFORE jax loads.

This is the reference's multi-process-on-localhost pattern (SURVEY.md §4)
mapped to TPU testing: a virtual 8-device mesh exercises every sharding
path without hardware.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import jax  # noqa: E402

# the axon TPU plugin ignores JAX_PLATFORMS; force via config
jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.devices()
assert len(jax.devices()) == 8, jax.devices()

# Persistent compile cache: the suite compiles the same tiny-model HLO
# hundreds of times across files (fresh Python objects defeat the
# in-process jit cache, but the HLO hash matches).  Measured 5.03s ->
# 1.08s per repeated tiny-GPT TrainStep compile; keyed on HLO so code
# changes invalidate naturally.  Opt out with PADDLE_TPU_TEST_CACHE=0.
_cache_dir = os.environ.get("PADDLE_TPU_TEST_CACHE",
                            "/tmp/paddle_tpu_test_jax_cache")
if _cache_dir != "0":
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_all():
    import paddle_tpu
    paddle_tpu.seed(102)
    yield


def pytest_collection_modifyitems(config, items):
    """Skip tests listed in tools/flaky_quarantine.txt (reference parity:
    tools/get_quick_disable_lt.py flaky quarantine), and gate
    mesh-marked tests on the device pool: mp/dp-sharded serving needs
    >= 4 devices, which the XLA_FLAGS forcing above provides — but a
    caller-set XLA_FLAGS (respected, line 12) may provide fewer, and
    those tests must SKIP loudly rather than fail on mesh build."""
    if len(jax.devices()) < 4:
        mesh_skip = pytest.mark.skip(
            reason=f"mesh tests need >= 4 devices, have "
                   f"{len(jax.devices())} — force a virtual pool via "
                   "XLA_FLAGS=--xla_force_host_platform_device_count")
        for item in items:
            if "mesh" in item.keywords:
                item.add_marker(mesh_skip)
    qpath = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "flaky_quarantine.txt")
    if not os.path.exists(qpath):
        return
    with open(qpath) as f:
        quarantined = {line.strip() for line in f
                       if line.strip() and not line.startswith("#")}
    if not quarantined:
        return
    marker = pytest.mark.skip(reason="quarantined-flaky (tools/"
                              "flaky_quarantine.txt)")
    for item in items:
        if item.nodeid in quarantined or \
                item.nodeid.split("::")[0] in quarantined:
            item.add_marker(marker)
