"""Sequence ops + text datasets tests (reference: sequence_ops/*,
edit_distance_op, python/paddle/text/datasets)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import text
from paddle_tpu.io import DataLoader


def test_sequence_pad_unpad_roundtrip():
    seqs = [np.arange(3 * 2).reshape(3, 2).astype(np.float32),
            np.ones((1, 2), np.float32),
            np.full((2, 2), 7.0, np.float32)]
    padded, lens = F.sequence_pad(seqs, pad_value=0.0)
    assert padded.shape == [3, 3, 2]
    assert list(lens.numpy()) == [3, 1, 2]
    assert np.all(padded.numpy()[1, 1:] == 0)
    flat = F.sequence_unpad(padded, lens)
    assert np.allclose(flat.numpy(), np.concatenate(seqs, axis=0))


def test_sequence_pool_types():
    x = np.array([[[1.0], [2.0], [3.0]],
                  [[4.0], [5.0], [0.0]]], np.float32)
    lens = np.array([3, 2], np.int64)
    xp, lp = paddle.to_tensor(x), paddle.to_tensor(lens)
    assert np.allclose(F.sequence_pool(xp, "sum", lp).numpy(),
                       [[6.0], [9.0]])
    assert np.allclose(F.sequence_pool(xp, "average", lp).numpy(),
                       [[2.0], [4.5]])
    assert np.allclose(F.sequence_pool(xp, "max", lp).numpy(),
                       [[3.0], [5.0]])
    assert np.allclose(F.sequence_pool(xp, "sqrt", lp).numpy(),
                       [[6 / np.sqrt(3)], [9 / np.sqrt(2)]])
    assert np.allclose(F.sequence_pool(xp, "first", lp).numpy(),
                       [[1.0], [4.0]])
    assert np.allclose(F.sequence_pool(xp, "last", lp).numpy(),
                       [[3.0], [5.0]])


def test_sequence_pool_gradient_masks_padding():
    x = paddle.to_tensor(np.ones((2, 3, 1), np.float32))
    x.stop_gradient = False
    lens = paddle.to_tensor(np.array([3, 1], np.int64))
    F.sequence_pool(x, "sum", lens).sum().backward()
    g = x.grad.numpy()[:, :, 0]
    assert np.allclose(g, [[1, 1, 1], [1, 0, 0]])


def test_sequence_softmax_and_reverse():
    x = np.array([[1.0, 2.0, 3.0], [1.0, 1.0, 9.0]], np.float32)
    lens = np.array([3, 2], np.int64)
    p = F.sequence_softmax(paddle.to_tensor(x), paddle.to_tensor(lens))
    assert np.allclose(p.numpy()[0], np.exp(x[0]) / np.exp(x[0]).sum(),
                       atol=1e-5)
    assert np.allclose(p.numpy()[1], [0.5, 0.5, 0.0])

    r = F.sequence_reverse(paddle.to_tensor(x[..., None]),
                           paddle.to_tensor(lens))
    assert np.allclose(r.numpy()[0, :, 0], [3.0, 2.0, 1.0])
    assert np.allclose(r.numpy()[1, :, 0], [1.0, 1.0, 9.0])


def test_sequence_expand():
    x = np.array([[1.0], [2.0]], np.float32)
    out = F.sequence_expand(paddle.to_tensor(x),
                            paddle.to_tensor(np.array([2, 3], np.int64)))
    assert np.allclose(out.numpy()[:, 0], [1, 1, 2, 2, 2])


def test_edit_distance():
    # "kitten" -> "sitting" distance 3 (classic)
    hyp = np.array([[ord(c) for c in "kitten "]], np.int64)
    ref = np.array([[ord(c) for c in "sitting"]], np.int64)
    d, n = F.edit_distance(paddle.to_tensor(hyp), paddle.to_tensor(ref),
                           normalized=False,
                           input_length=paddle.to_tensor(
                               np.array([6], np.int64)),
                           label_length=paddle.to_tensor(
                               np.array([7], np.int64)))
    assert d.numpy()[0, 0] == 3.0
    assert n.numpy()[0] == 1
    dn, _ = F.edit_distance(paddle.to_tensor(hyp), paddle.to_tensor(ref),
                            normalized=True,
                            input_length=paddle.to_tensor(
                                np.array([6], np.int64)),
                            label_length=paddle.to_tensor(
                                np.array([7], np.int64)))
    assert np.allclose(dn.numpy()[0, 0], 3.0 / 7.0)


def test_text_datasets_shapes(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_SYNTH_N", "32")
    imdb = text.Imdb(mode="train")
    doc, label = imdb[0]
    assert doc.dtype == np.int64 and label.shape == (1,)
    assert len(imdb) == 32

    ngram = text.Imikolov(mode="train", window_size=5)
    item = ngram[0]
    assert len(item) == 5

    conll = text.Conll05st(mode="train")
    rec = conll[0]
    assert len(rec) == 9  # words + 5 ctx + pred + mark + labels
    words, labels = rec[0], rec[-1]
    assert words.shape == labels.shape

    ml = text.Movielens(mode="train")
    assert len(ml[0]) == 8

    housing = text.UCIHousing(mode="train")
    feat, price = housing[0]
    assert feat.shape == (13,) and price.shape == (1,)

    wmt = text.WMT14(mode="train", dict_size=1000)
    src, trg, trg_next = wmt[0]
    assert trg[0] == 0 and trg_next[-1] == 1  # <s> ... </s>
    assert len(trg) == len(trg_next)


def test_uci_housing_trains(monkeypatch):
    """End-to-end: linear regression on synthetic UCIHousing converges."""
    monkeypatch.setenv("PADDLE_TPU_SYNTH_N", "256")
    paddle.seed(0)
    ds = text.UCIHousing(mode="train")
    from paddle_tpu import nn, optimizer
    net = nn.Linear(13, 1)
    opt = optimizer.Adam(learning_rate=0.01,
                         parameters=net.parameters())
    loader = DataLoader(ds, batch_size=32, shuffle=True)
    losses = []
    for epoch in range(5):
        for feat, price in loader:
            loss = ((net(feat) - price) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0]


def test_sequence_erase_matches_numpy():
    from paddle_tpu.nn.functional.sequence import sequence_erase
    x = np.array([[2, 1, 3, 1, 5], [1, 1, 2, 0, 0]], np.int64)
    lens = np.array([5, 3], np.int64)
    out, new_len = sequence_erase(x, [1], lengths=lens)
    np.testing.assert_array_equal(new_len.numpy(), [3, 1])
    np.testing.assert_array_equal(out.numpy()[0, :3], [2, 3, 5])
    np.testing.assert_array_equal(out.numpy()[1, :1], [2])
    assert (out.numpy()[0, 3:] == 0).all()


def test_sequence_topk_avg_pooling_basic():
    from paddle_tpu.nn.functional.sequence import sequence_topk_avg_pooling
    rs = np.random.RandomState(0)
    x = rs.rand(2, 3, 4, 6).astype(np.float32)
    row_l = np.array([4, 2], np.int64)
    col_l = np.array([6, 3], np.int64)
    out = sequence_topk_avg_pooling(x, row_l, col_l, topks=[1, 3],
                                    channel_num=3)
    assert out.shape == [2, 4, 6]  # [B, R, C*K]
    # numpy check for batch 0, channel 1, row 2, k=3
    ref = np.sort(x[0, 1, 2])[::-1][:3].mean()
    np.testing.assert_allclose(out.numpy()[0, 2, 1 * 2 + 1], ref,
                               rtol=1e-5)
    # masked rows are zero
    assert (out.numpy()[1, 2:] == 0).all()
