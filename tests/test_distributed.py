"""Distributed tests on the 8-device virtual CPU mesh.

SURVEY.md §4 mapping: the reference's multi-process-localhost distributed
tests become multi-device single-host mesh tests; "assert on the rewritten
program" becomes "assert on shardings / numerical equivalence".
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu
from paddle_tpu import nn, optimizer
import paddle_tpu.distributed as dist
from paddle_tpu.parallel.train_step import TrainStep

rng = np.random.RandomState(7)


@pytest.fixture
def dp_mesh():
    mesh = dist.build_mesh(dp=8)
    dist.set_mesh(mesh)
    yield mesh
    dist.set_mesh(None)


@pytest.fixture
def hybrid_mesh():
    mesh = dist.build_mesh(dp=2, mp=2, pp=2)
    dist.set_mesh(mesh)
    yield mesh
    dist.set_mesh(None)


@pytest.fixture
def sharding_mesh():
    mesh = dist.build_mesh(dp=2, sharding=4)
    dist.set_mesh(mesh)
    yield mesh
    dist.set_mesh(None)


class TestMesh:
    def test_build_default_all_dp(self):
        mesh = dist.build_mesh()
        assert mesh.shape["dp"] == 8
        assert mesh.shape["mp"] == 1

    def test_build_hybrid(self):
        mesh = dist.build_mesh(dp=2, mp=2, pp=2)
        assert mesh.shape == {"dp": 2, "sharding": 1, "pp": 2, "mp": 2,
                              "sp": 1, "ep": 1}

    def test_mismatch_raises(self):
        with pytest.raises(ValueError):
            dist.build_mesh(dp=3, mp=2)


class TestCollectives:
    def test_allreduce_inside_region(self, dp_mesh):
        def fn(x):
            t = paddle_tpu.Tensor(x)
            dist.all_reduce(t)
            return t._data

        sharded = dist.parallel_region(fn, axis="dp")
        x = np.arange(8, dtype=np.float32)
        out = np.asarray(jax.jit(sharded)(x))
        np.testing.assert_allclose(out, np.full(8, x.sum()))

    def test_allgather_inside_region(self, dp_mesh):
        def fn(x):
            t = paddle_tpu.Tensor(x)
            outs = []
            dist.all_gather(outs, t)
            return jnp.stack([o._data for o in outs]).reshape(-1)

        sharded = dist.parallel_region(
            fn, axis="dp", out_specs=P("dp"))
        x = np.arange(8, dtype=np.float32)
        out = np.asarray(jax.jit(sharded)(x))
        # each device returns all 8 values; the dp-sharded output stacks
        assert out.shape == (64,)
        np.testing.assert_allclose(out[:8], x)

    def test_broadcast(self, dp_mesh):
        def fn(x):
            t = paddle_tpu.Tensor(x)
            dist.broadcast(t, src=3)
            return t._data

        sharded = dist.parallel_region(fn, axis="dp")
        x = np.arange(8, dtype=np.float32)
        out = np.asarray(jax.jit(sharded)(x))
        np.testing.assert_allclose(out, np.full(8, 3.0))

    def test_reduce_op_variants(self, dp_mesh):
        for op, expect in [(dist.ReduceOp.MAX, 7.0),
                           (dist.ReduceOp.MIN, 0.0),
                           (dist.ReduceOp.AVG, 3.5)]:
            def fn(x):
                t = paddle_tpu.Tensor(x)
                dist.all_reduce(t, op=op)
                return t._data

            out = np.asarray(jax.jit(dist.parallel_region(fn, axis="dp"))(
                np.arange(8, dtype=np.float32)))
            np.testing.assert_allclose(out, np.full(8, expect))

    def test_p2p_shift(self, dp_mesh):
        def fn(x):
            return dist.p2p_shift(paddle_tpu.Tensor(x), axis="dp",
                                  shift=1)._data

        out = np.asarray(jax.jit(dist.parallel_region(fn, axis="dp"))(
            np.arange(8, dtype=np.float32)))
        np.testing.assert_allclose(out, np.roll(np.arange(8), 1))

    def test_eager_world1_identity(self):
        t = paddle_tpu.ones([4])
        dist.all_reduce(t)
        np.testing.assert_allclose(t.numpy(), np.ones(4))


def _make_regression(n=64, din=8, dout=4, seed=0):
    r = np.random.RandomState(seed)
    w = r.rand(din, dout).astype(np.float32)
    x = r.rand(n, din).astype(np.float32)
    y = x @ w + 0.1
    return x, y


def _mlp(seed=0):
    paddle_tpu.seed(seed)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))


class TestTrainStepDP:
    def test_dp_matches_single_device(self, dp_mesh):
        x, y = _make_regression()
        loss_fn = nn.MSELoss()

        # single-device eager reference
        net_ref = _mlp(seed=11)
        opt_ref = optimizer.SGD(learning_rate=0.1,
                                parameters=net_ref.parameters())
        losses_ref = []
        for _ in range(5):
            loss = loss_fn(net_ref(paddle_tpu.to_tensor(x)),
                           paddle_tpu.to_tensor(y))
            loss.backward()
            opt_ref.step()
            opt_ref.clear_grad()
            losses_ref.append(float(loss.numpy()))

        # 8-way DP compiled step on the same data
        net_dp = _mlp(seed=11)
        opt_dp = optimizer.SGD(learning_rate=0.1,
                               parameters=net_dp.parameters())
        step = TrainStep(net_dp, opt_dp, loss_fn=loss_fn)
        losses_dp = [float(step.step([x], [y]).numpy()) for _ in range(5)]

        np.testing.assert_allclose(losses_ref, losses_dp, rtol=1e-4)
        step.sync_to_layer()
        np.testing.assert_allclose(net_dp[0].weight.numpy(),
                                   net_ref[0].weight.numpy(), rtol=1e-4)

    def test_batch_is_sharded(self, dp_mesh):
        net = _mlp()
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=net.parameters())
        step = TrainStep(net, opt, loss_fn=nn.MSELoss())
        x, y = _make_regression()
        step.step([x], [y])
        # params stay replicated
        w = step.params["0.weight"]
        assert w.sharding.spec == P() or all(
            s is None for s in w.sharding.spec)

    def test_adam_dp_converges(self, dp_mesh):
        net = _mlp(seed=3)
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=net.parameters())
        step = TrainStep(net, opt, loss_fn=nn.MSELoss())
        x, y = _make_regression()
        first = float(step.step([x], [y]).numpy())
        for _ in range(50):
            last = float(step.step([x], [y]).numpy())
        assert last < first * 0.2


class TestTrainStepFSDP:
    def test_stage3_param_sharding_and_equivalence(self, sharding_mesh):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        x, y = _make_regression()
        loss_fn = nn.MSELoss()

        net_ref = _mlp(seed=21)
        opt_ref = optimizer.Adam(learning_rate=0.01,
                                 parameters=net_ref.parameters())
        ref_losses = []
        for _ in range(3):
            loss = loss_fn(net_ref(paddle_tpu.to_tensor(x)),
                           paddle_tpu.to_tensor(y))
            loss.backward()
            opt_ref.step()
            opt_ref.clear_grad()
            ref_losses.append(float(loss.numpy()))

        strategy = DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs["stage"] = 3
        strategy.sharding_configs["min_shard_size"] = 1
        net = _mlp(seed=21)
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=net.parameters())
        step = TrainStep(net, opt, loss_fn=loss_fn, strategy=strategy,
                         donate=False)
        # weights of fc1 (8x32) should be sharded over 'sharding' (4-way)
        spec = step.param_specs["0.weight"]
        assert spec != P()
        losses = [float(step.step([x], [y]).numpy()) for _ in range(3)]
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-3)

    def test_stage2_opt_state_sharded(self, sharding_mesh):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        strategy = DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs["stage"] = 2
        net = _mlp()
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=net.parameters())
        step = TrainStep(net, opt, loss_fn=nn.MSELoss(),
                         strategy=strategy)
        # params replicated, moments sharded
        assert step.param_specs["0.weight"] == P()
        assert step.opt_specs["0.weight"]["moment1"] == P("sharding")


class TestTensorParallel:
    def test_col_row_parallel_equivalence(self, hybrid_mesh):
        """Megatron pair (col-parallel -> row-parallel) == dense 2-layer."""
        from paddle_tpu.distributed.sharding import (
            ColumnParallelLinear, RowParallelLinear)
        paddle_tpu.seed(5)

        class TPNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = ColumnParallelLinear(8, 32,
                                                gather_output=False)
                self.fc2 = RowParallelLinear(32, 4,
                                             input_is_parallel=True)

            def forward(self, x):
                return self.fc2(nn.functional.relu(self.fc1(x)))

        net = TPNet()
        x, y = _make_regression()
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=net.parameters())
        step = TrainStep(net, opt, loss_fn=nn.MSELoss(), donate=False)
        # weight specs must carry 'mp'
        assert step.param_specs["fc1.weight"] == P(None, "mp")
        assert step.param_specs["fc2.weight"] == P("mp", None)

        # dense reference with identical weights
        dense = nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                              nn.Linear(32, 4))
        dense[0].weight.set_value(net.fc1.weight.numpy())
        dense[0].bias.set_value(net.fc1.bias.numpy())
        dense[2].weight.set_value(net.fc2.weight.numpy())
        dense[2].bias.set_value(net.fc2.bias.numpy())
        opt_d = optimizer.SGD(learning_rate=0.1,
                              parameters=dense.parameters())
        loss_fn = nn.MSELoss()
        ref = []
        for _ in range(3):
            loss = loss_fn(dense(paddle_tpu.to_tensor(x)),
                           paddle_tpu.to_tensor(y))
            loss.backward()
            opt_d.step()
            opt_d.clear_grad()
            ref.append(float(loss.numpy()))
        tp_losses = [float(step.step([x], [y]).numpy()) for _ in range(3)]
        np.testing.assert_allclose(tp_losses, ref, rtol=1e-3)

    def test_vocab_parallel_embedding(self, hybrid_mesh):
        from paddle_tpu.distributed.sharding import VocabParallelEmbedding
        emb = VocabParallelEmbedding(16, 8)
        out = emb(paddle_tpu.to_tensor(np.array([[1, 3], [5, 7]])))
        assert out.shape == [2, 2, 8]
        np.testing.assert_allclose(out.numpy()[0, 0],
                                   emb.weight.numpy()[1], rtol=1e-6)


class TestRingAttention:
    def test_ring_matches_dense(self):
        mesh = dist.build_mesh(dp=1, sp=8)
        dist.set_mesh(mesh)
        try:
            b, s, h, d = 2, 32, 2, 8
            q = rng.rand(b, s, h, d).astype(np.float32)
            k = rng.rand(b, s, h, d).astype(np.float32)
            v = rng.rand(b, s, h, d).astype(np.float32)
            from paddle_tpu.nn.functional.attention import (
                _reference_attention)
            ref = _reference_attention(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v), None, None, False)
            out = dist.ring_attention(q, k, v, axis="sp", causal=False)
            np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)
        finally:
            dist.set_mesh(None)

    def test_ring_causal_matches_dense(self):
        mesh = dist.build_mesh(dp=1, sp=8)
        dist.set_mesh(mesh)
        try:
            b, s, h, d = 1, 32, 2, 8
            q = rng.rand(b, s, h, d).astype(np.float32)
            k = rng.rand(b, s, h, d).astype(np.float32)
            v = rng.rand(b, s, h, d).astype(np.float32)
            from paddle_tpu.nn.functional.attention import (
                _reference_attention)
            ref = _reference_attention(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v), None, None, True)
            out = dist.ring_attention(q, k, v, axis="sp", causal=True)
            np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)
        finally:
            dist.set_mesh(None)

    def test_ulysses_matches_dense(self):
        mesh = dist.build_mesh(dp=1, sp=8)
        dist.set_mesh(mesh)
        try:
            b, s, h, d = 1, 32, 8, 4
            q = rng.rand(b, s, h, d).astype(np.float32)
            k = rng.rand(b, s, h, d).astype(np.float32)
            v = rng.rand(b, s, h, d).astype(np.float32)
            from paddle_tpu.nn.functional.attention import (
                _reference_attention)
            from paddle_tpu.distributed.ring import ulysses_attention
            ref = _reference_attention(jnp.asarray(q), jnp.asarray(k),
                                       jnp.asarray(v), None, None, True)
            out = ulysses_attention(q, k, v, axis="sp", causal=True)
            np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                                       rtol=1e-4, atol=1e-5)
        finally:
            dist.set_mesh(None)


class TestPipeline:
    def test_pipeline_forward_matches_sequential(self, hybrid_mesh):
        from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer
        from paddle_tpu.parallel.pipeline import (
            stack_block_params, build_pipeline_fn)
        paddle_tpu.seed(9)
        blocks = [nn.Linear(8, 8) for _ in range(4)]
        pipe = PipelineLayer(pre=None, blocks=blocks, post=None)
        pipe.eval()
        M = 2
        fwd, pnames, bnames = build_pipeline_fn(
            pipe, num_microbatches=M, mesh=hybrid_mesh, training=False)
        _, stacked = stack_block_params(pipe.blocks)
        pp = hybrid_mesh.shape["pp"]
        block_stacked = {k: v.reshape((pp, len(blocks) // pp)
                                      + v.shape[1:])
                         for k, v in stacked.items()}
        x = rng.rand(4, 8).astype(np.float32)
        key = jax.random.key(0)
        out, _ = jax.jit(lambda bs, xx: fwd({}, bs, {}, xx, key))(
            block_stacked, jnp.asarray(x))
        ref = pipe(paddle_tpu.to_tensor(x)).numpy()
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-5)

    def test_pipeline_train_step_converges(self, hybrid_mesh):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer
        paddle_tpu.seed(13)
        blocks = [nn.Sequential(nn.Linear(8, 8), nn.Tanh())
                  for _ in range(4)]
        pipe = PipelineLayer(pre=nn.Linear(8, 8), blocks=blocks,
                             post=nn.Linear(8, 4))
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs["accumulate_steps"] = 2
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=pipe.parameters())
        step = TrainStep(pipe, opt, loss_fn=nn.MSELoss(),
                         strategy=strategy, donate=False)
        assert step.is_pipeline
        x, y = _make_regression(n=16)
        first = float(step.step([x], [y]).numpy())
        for _ in range(30):
            last = float(step.step([x], [y]).numpy())
        assert last < first * 0.5

    def test_pipeline_eager_forward(self):
        from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer
        pipe = PipelineLayer(pre=nn.Linear(4, 8),
                             blocks=[nn.Linear(8, 8) for _ in range(2)],
                             post=nn.Linear(8, 2))
        out = pipe(paddle_tpu.ones([3, 4]))
        assert out.shape == [3, 2]


class TestFleet:
    def test_fleet_init_builds_mesh(self):
        from paddle_tpu.distributed import fleet
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        try:
            mesh = dist.get_mesh()
            assert mesh.shape["mp"] == 2 and mesh.shape["pp"] == 2
            hcg = fleet.get_hybrid_communicate_group()
            assert hcg.get_model_parallel_world_size() == 2
        finally:
            dist.set_mesh(None)

    def test_distributed_optimizer_wraps(self):
        from paddle_tpu.distributed import fleet
        net = _mlp()
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=net.parameters())
        strategy = fleet.DistributedStrategy()
        dopt = fleet.distributed_optimizer(opt, strategy)
        assert dopt.get_lr() == 0.01

    def test_strategy_save_load(self, tmp_path):
        from paddle_tpu.distributed import fleet
        s = fleet.DistributedStrategy()
        s.sharding = True
        path = str(tmp_path / "strategy.txt")
        s.save_to_prototxt(path)
        s2 = fleet.DistributedStrategy()
        s2.load_from_prototxt(path)
        assert s2.sharding is True


class TestGradientMerge:
    def test_merge_matches_large_batch(self, dp_mesh):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        x, y = _make_regression(n=32)
        loss_fn = nn.MSELoss()

        net_a = _mlp(seed=31)
        opt_a = optimizer.SGD(learning_rate=0.1,
                              parameters=net_a.parameters())
        step_a = TrainStep(net_a, opt_a, loss_fn=loss_fn, donate=False)
        la = float(step_a.step([x], [y]).numpy())

        strategy = DistributedStrategy()
        strategy.gradient_merge = True
        strategy.gradient_merge_configs["k_steps"] = 4
        net_b = _mlp(seed=31)
        opt_b = optimizer.SGD(learning_rate=0.1,
                              parameters=net_b.parameters())
        step_b = TrainStep(net_b, opt_b, loss_fn=loss_fn,
                           strategy=strategy, donate=False)
        lb = float(step_b.step([x], [y]).numpy())
        np.testing.assert_allclose(la, lb, rtol=1e-4)
        step_a.sync_to_layer()
        step_b.sync_to_layer()
        np.testing.assert_allclose(net_a[0].weight.numpy(),
                                   net_b[0].weight.numpy(), rtol=1e-4)


@pytest.mark.slow
def test_pipeline_recompute_matches_plain():
    """Per-tick remat must not change pipeline numerics (only memory)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu import distributed as dist
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.models.gpt import gpt_pipe_model
    from paddle_tpu.parallel.train_step import TrainStep

    mesh = dist.build_mesh(pp=2, dp=4)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (8, 16 + 1)).astype(np.int32)
    x, y = ids[:, :-1], ids[:, 1:]

    losses = {}
    for remat in (False, True):
        paddle.seed(0)
        model = gpt_pipe_model("tiny", dropout=0.0)
        strategy = DistributedStrategy()
        strategy.pipeline = True
        strategy.pipeline_configs = {"accumulate_steps": 2}
        strategy.recompute = remat
        from paddle_tpu.models import GPTPretrainingCriterion
        step = TrainStep(model, optimizer.SGD(
            learning_rate=0.1, parameters=model.parameters()),
            loss_fn=GPTPretrainingCriterion(), strategy=strategy,
            mesh=mesh)
        vals = [float(step.step([x], [y]).numpy()) for _ in range(3)]
        losses[remat] = vals
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
    assert losses[False][-1] < losses[False][0]


class TestRingAttentionTraining:
    """Round-2: the ring loop is a lax.scan, so ring attention is
    reverse-differentiable — sequence parallelism trains (round-1 was
    forward-only)."""

    def test_grads_match_dense(self):
        import jax.numpy as jnp
        from paddle_tpu.distributed.ring import ring_attention
        from paddle_tpu.nn.functional.attention import \
            _reference_attention
        mesh = dist.build_mesh(dp=4, sp=2)
        dist.set_mesh(mesh)
        try:
            rs = np.random.RandomState(0)
            q = rs.randn(2, 16, 2, 8).astype(np.float32)
            k = rs.randn(2, 16, 2, 8).astype(np.float32)
            v = rs.randn(2, 16, 2, 8).astype(np.float32)
            for causal in (False, True):
                def loss_ring(qq):
                    return jnp.sum(ring_attention(
                        qq, k, v, axis="sp", causal=causal)._data ** 2)

                def loss_ref(qq):
                    return jnp.sum(_reference_attention(
                        qq, jnp.asarray(k), jnp.asarray(v), None, None,
                        causal) ** 2)

                g_ring = jax.grad(loss_ring)(jnp.asarray(q))
                g_ref = jax.grad(loss_ref)(jnp.asarray(q))
                np.testing.assert_allclose(np.asarray(g_ring),
                                           np.asarray(g_ref),
                                           rtol=2e-3, atol=2e-4)
        finally:
            dist.set_mesh(None)

    @staticmethod
    def _run_sp_losses(use_sp, sp, ids):
        from paddle_tpu.models import GPTModel, GPTPretrainingCriterion
        mesh = dist.build_mesh(dp=8 // sp, sp=sp)
        dist.set_mesh(mesh)
        paddle_tpu.seed(0)
        model = GPTModel.from_config("tiny", dropout=0.0, use_sp=use_sp)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = TrainStep(model, opt, loss_fn=GPTPretrainingCriterion(),
                         donate=False)
        return [float(step.step([ids[:, :-1]], [ids[:, 1:]]).numpy())
                for _ in range(3)]

    @pytest.mark.parametrize("use_sp,sp", [
        pytest.param(True, 4, marks=pytest.mark.slow),
        ("ulysses", 2)])
    def test_sp_model_trains_and_matches_dense(self, use_sp, sp):
        ids = np.random.RandomState(0).randint(0, 128, (4, 33)) \
            .astype(np.int64)
        try:
            sp_losses = self._run_sp_losses(use_sp, sp, ids)
            dense_losses = self._run_sp_losses(False, 1, ids)
            assert sp_losses[-1] < sp_losses[0]
            np.testing.assert_allclose(sp_losses, dense_losses,
                                       rtol=2e-3, atol=2e-3)
        finally:
            dist.set_mesh(None)

    def test_ulysses_indivisible_heads_clear_error(self):
        from paddle_tpu.distributed.ring import ulysses_attention
        mesh = dist.build_mesh(dp=2, sp=4)
        dist.set_mesh(mesh)
        try:
            rs = np.random.RandomState(0)
            q = rs.randn(2, 16, 3, 8).astype(np.float32)  # 3 heads, sp=4
            with pytest.raises(ValueError, match="not\\s+divisible"):
                ulysses_attention(q, q, q, axis="sp")
        finally:
            dist.set_mesh(None)

    @pytest.mark.slow
    def test_ring_dropout_trains_and_masks(self):
        """Attention dropout under sp: training runs finite, masks vary
        across steps, dropout=0 path unchanged."""
        from paddle_tpu.models import GPTModel, GPTPretrainingCriterion
        mesh = dist.build_mesh(dp=2, sp=4)
        dist.set_mesh(mesh)
        try:
            paddle_tpu.seed(0)
            model = GPTModel.from_config("tiny", dropout=0.2,
                                         use_sp=True)
            opt = optimizer.AdamW(learning_rate=1e-3,
                                  parameters=model.parameters())
            step = TrainStep(model, opt,
                             loss_fn=GPTPretrainingCriterion(),
                             donate=False)
            ids = np.random.RandomState(0).randint(0, 128, (4, 33)) \
                .astype(np.int64)
            losses = [float(step.step([ids[:, :-1]],
                                      [ids[:, 1:]]).numpy())
                      for _ in range(4)]
            assert all(np.isfinite(losses))
            assert losses[-1] < losses[0]
            # eval forward (dropout off) must EQUAL the same weights run
            # through a dropout=0 model — dropout leaking into eval
            # would break this
            model.eval()
            out1 = model(paddle_tpu.to_tensor(ids[:2, :-1])).numpy()
            clean = GPTModel.from_config("tiny", dropout=0.0,
                                         use_sp=True)
            clean.set_state_dict(model.state_dict())
            clean.eval()
            out2 = clean(paddle_tpu.to_tensor(ids[:2, :-1])).numpy()
            np.testing.assert_allclose(out1, out2, rtol=1e-5,
                                       atol=1e-6)
        finally:
            dist.set_mesh(None)

    @pytest.mark.slow
    def test_ulysses_dropout_trains_and_matches_ring(self):
        """use_sp='ulysses' with dropout>0 trains (the round-2 raise is
        gone); its loss trajectory stays close to ring-sp's — same model,
        same data, both applying probs-dropout, only the comm pattern
        differs."""
        from paddle_tpu.models import GPTModel, GPTPretrainingCriterion
        mesh = dist.build_mesh(dp=2, sp=4)  # heads=4 % sp==0
        dist.set_mesh(mesh)
        try:
            ids = np.random.RandomState(1).randint(0, 128, (4, 33)) \
                .astype(np.int64)

            def run(use_sp):
                paddle_tpu.seed(0)
                model = GPTModel.from_config("tiny", dropout=0.2,
                                             use_sp=use_sp)
                opt = optimizer.AdamW(learning_rate=1e-3,
                                      parameters=model.parameters())
                step = TrainStep(model, opt,
                                 loss_fn=GPTPretrainingCriterion(),
                                 donate=False)
                return [float(step.step([ids[:, :-1]],
                                        [ids[:, 1:]]).numpy())
                        for _ in range(4)]

            ul = run("ulysses")
            assert all(np.isfinite(ul))
            assert ul[-1] < ul[0]
            ring = run(True)
            # identical weights/data; dropout masks differ (different key
            # folding), so trajectories agree loosely, not bitwise
            np.testing.assert_allclose(ul, ring, rtol=0.05)
        finally:
            dist.set_mesh(None)

    @pytest.mark.slow
    def test_ulysses_dropout_eval_unaffected(self):
        """Eval forward with ulysses must equal the dropout=0 model."""
        from paddle_tpu.models import GPTModel
        mesh = dist.build_mesh(dp=2, sp=4)
        dist.set_mesh(mesh)
        try:
            paddle_tpu.seed(0)
            ids = np.random.RandomState(2).randint(0, 128, (2, 32)) \
                .astype(np.int64)
            model = GPTModel.from_config("tiny", dropout=0.3,
                                         use_sp="ulysses")
            model.eval()
            out1 = model(paddle_tpu.to_tensor(ids)).numpy()
            clean = GPTModel.from_config("tiny", dropout=0.0,
                                         use_sp="ulysses")
            clean.set_state_dict(model.state_dict())
            clean.eval()
            out2 = clean(paddle_tpu.to_tensor(ids)).numpy()
            np.testing.assert_allclose(out1, out2, rtol=1e-5, atol=1e-6)
        finally:
            dist.set_mesh(None)
