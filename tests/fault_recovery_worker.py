"""Worker for the kill-and-resume fault-recovery test: deterministic
training under TrainEpochRange; optionally dies HARD (os._exit, the
SIGKILL/preemption analogue) right after a given epoch's snapshot.
Writes final weights to OUT_PATH when it survives all epochs."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.incubate.checkpoint import TrainEpochRange

kill_after = int(os.environ.get("KILL_AFTER_EPOCH", "-1"))
out_path = os.environ["OUT_PATH"]

paddle.seed(7)
net = nn.Sequential(nn.Linear(6, 16), nn.Tanh(), nn.Linear(16, 2))
opt = optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                         parameters=net.parameters())
r = TrainEpochRange(6, name="faultjob").attach(net, opt)

rs = np.random.RandomState(0)
x = paddle.to_tensor(rs.rand(8, 6).astype("float32"))
y = paddle.to_tensor(rs.randint(0, 2, (8,)).astype("int64"))
lossf = nn.CrossEntropyLoss()

for epoch in r.get():
    # 3 deterministic steps per epoch
    for _ in range(3):
        loss = lossf(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    print(f"EPOCH {epoch} loss {float(loss.numpy()):.6f}", flush=True)
    if epoch == kill_after:
        # hard death BEFORE this epoch's snapshot (get() saves after
        # the yield returns): the resume must REDO this epoch
        os._exit(137)

state = {k: v.numpy() for k, v in net.state_dict().items()}
np.savez(out_path, **state)
print("DONE", flush=True)
