"""Worker for the 2-process localhost distributed test (reference pattern:
unittests/test_collective_base.py — ranks run the same script, results are
printed for the parent to compare)."""
import os
import sys

rank = int(sys.argv[1])
port = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["PADDLE_TRAINERS_NUM"] = "2"
os.environ["PADDLE_TRAINER_ID"] = str(rank)
os.environ["PADDLE_TRAINER_ENDPOINTS"] = f"127.0.0.1:{port}"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")

# The launcher (paddle_tpu.distributed.launch) initializes jax.distributed
# BEFORE the user script imports the framework — replicate that here (the
# framework import touches the XLA backend).
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=rank)

import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu import distributed as dist

# env-contract bootstrap (no-op here since the launcher already
# initialized; still builds the default mesh)
dist.init_parallel_env()
assert jax.process_count() == 2, jax.process_count()
assert dist.get_rank() == rank

mesh = dist.build_mesh(dp=4)   # 2 procs x 2 local devices
dist.set_mesh(mesh)

# cross-process psum through the collective API
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def summed(x):
    return jax.lax.psum(x, "dp")


from jax.experimental.shard_map import shard_map
local = np.full((2, 1), float(rank + 1), np.float32)
glob = dist.mesh.host_local_to_global(local, mesh, "dp", None)
out = jax.jit(shard_map(summed, mesh=mesh, in_specs=P("dp"),
                        out_specs=P("dp")))(glob)
total = float(np.asarray(out.addressable_shards[0].data)[0, 0])
# ranks contribute 1+1+2+2 = 6 over the 4 shards
assert total == 6.0, total
print(f"RESULT psum {rank} {total}", flush=True)

# data-parallel training: per-rank local shard of a shared problem
from paddle_tpu.parallel.train_step import TrainStep


class MSE(nn.Layer):
    def forward(self, p, l):
        return paddle.mean((p - l) ** 2)


paddle.seed(0)   # identical init on both ranks
net = nn.Linear(8, 1)
step = TrainStep(net, optimizer.SGD(learning_rate=0.1,
                                    parameters=net.parameters()),
                 loss_fn=MSE(), mesh=mesh)
rng = np.random.RandomState(0)
x_global = rng.rand(16, 8).astype("float32")
w_true = rng.rand(8, 1).astype("float32")
y_global = x_global @ w_true
# each rank feeds its half (8 rows)
x_local = x_global[rank * 8:(rank + 1) * 8]
y_local = y_global[rank * 8:(rank + 1) * 8]
losses = []
for _ in range(5):
    loss = step.step([x_local], [y_local])
    losses.append(float(loss.numpy()))
print(f"RESULT losses {rank} " + ",".join(f"{v:.6f}" for v in losses),
      flush=True)

# multi-host pipeline parallelism: pp=2 spans the two processes (each
# stage lives on one host's devices) — the round-1 NotImplementedError
# lifted in parallel/train_step.py.  Both GPipe and 1F1B schedules.
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer

for schedule in ("F-then-B", "1F1B"):
    # device order (0,2,1,3): after the (dp, pp) reshape each pp pair is
    # (proc0-device, proc1-device), so the ppermute ring genuinely
    # crosses the process boundary (d0..d1 live on proc 0, d2..d3 on
    # proc 1 — the default order would keep pp within one host)
    devs = jax.devices()
    assert devs[0].process_index != devs[2].process_index, \
        [d.process_index for d in devs]
    mesh_pp = dist.build_mesh(dp=2, pp=2,
                              devices=[devs[0], devs[2],
                                       devs[1], devs[3]])
    for pair in mesh_pp.devices.reshape(2, 2):
        assert pair[0].process_index != pair[1].process_index, \
            "pp pair does not span processes"
    dist.set_mesh(mesh_pp)
    paddle.seed(0)
    blocks = [nn.Sequential(nn.Linear(8, 8), nn.Tanh())
              for _ in range(2)]
    pipe = PipelineLayer(pre=nn.Linear(8, 8), blocks=blocks,
                         post=nn.Linear(8, 1))
    strategy = DistributedStrategy()
    strategy.pipeline = True
    strategy.pipeline_configs["accumulate_steps"] = 2
    strategy.pipeline_configs["schedule_mode"] = schedule
    opt = optimizer.SGD(learning_rate=0.05,
                        parameters=pipe.parameters())
    pstep = TrainStep(pipe, opt, loss_fn=MSE(), strategy=strategy,
                      mesh=mesh_pp, donate=False)
    pl = []
    for _ in range(4):
        # multi-host pipeline contract: every process feeds the
        # identical GLOBAL batch (the pp ring spans hosts)
        loss = pstep.step([x_global], [y_global])
        pl.append(float(loss.numpy()))
    tag = "pp_gpipe" if schedule == "F-then-B" else "pp_1f1b"
    print(f"RESULT {tag} {rank} " + ",".join(f"{v:.6f}" for v in pl),
          flush=True)

print(f"RESULT done {rank}", flush=True)
