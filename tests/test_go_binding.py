"""Go binding consistency: the image has no Go toolchain, so validate the
cgo wrapper STATICALLY against the C API header (every C symbol the Go
code calls must exist in paddle_capi.h with matching names)."""
import os
import re

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(p):
    with open(os.path.join(ROOT, p)) as f:
        return f.read()


def test_go_calls_match_c_header():
    header = _read("paddle_tpu/csrc/paddle_capi.h")
    declared = set(re.findall(r"\bPD_\w+", header))
    go_src = ""
    godir = os.path.join(ROOT, "go", "paddle")
    for fn in os.listdir(godir):
        if fn.endswith(".go"):
            go_src += _read(os.path.join("go", "paddle", fn))
    used = set(re.findall(r"C\.(PD_\w+)", go_src))
    missing = used - declared
    assert not missing, f"Go binding calls undeclared C symbols: {missing}"
    # the core surface must be wrapped
    for sym in ("PD_NewConfig", "PD_ConfigSetModel", "PD_NewPredictor",
                "PD_SetInput", "PD_Run", "PD_GetOutput", "PD_LastError"):
        assert sym in used, f"Go binding does not wrap {sym}"


def test_go_files_have_cgo_preamble():
    pred = _read("go/paddle/predictor.go")
    cfg = _read("go/paddle/config.go")
    assert '#include "paddle_capi.h"' in pred
    assert "-lpaddle_capi" in cfg
