"""Double / higher-order gradients on the eager tape.

Reference parity: imperative/partial_grad_engine.cc (PartialGradEngine),
used by gradient-penalty training (WGAN-GP).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class TestDoubleGrad:
    def test_cubic_second_derivative(self):
        x = paddle.to_tensor(np.array([2.0, -1.5, 0.5], "float32"))
        x.stop_gradient = False
        y = paddle.sum(x ** 3)
        (g1,) = paddle.grad(y, x, create_graph=True)
        np.testing.assert_allclose(g1.numpy(), 3 * np.array(
            [2.0, -1.5, 0.5]) ** 2, rtol=1e-5)
        (g2,) = paddle.grad(paddle.sum(g1), x)
        np.testing.assert_allclose(g2.numpy(), 6 * np.array(
            [2.0, -1.5, 0.5]), rtol=1e-5)

    def test_triple_derivative(self):
        x = paddle.to_tensor(np.array([1.3], "float32"))
        x.stop_gradient = False
        y = x ** 4
        (g1,) = paddle.grad(y, x, create_graph=True)
        (g2,) = paddle.grad(g1, x, create_graph=True)
        (g3,) = paddle.grad(g2, x)
        np.testing.assert_allclose(g3.numpy(), [24 * 1.3], rtol=1e-5)

    def test_mixed_partial(self):
        x = paddle.to_tensor(np.array([2.0], "float32"))
        y = paddle.to_tensor(np.array([3.0], "float32"))
        x.stop_gradient = False
        y.stop_gradient = False
        z = (x ** 2) * (y ** 3)
        (gx,) = paddle.grad(z, x, create_graph=True)  # 2x y^3
        (gxy,) = paddle.grad(gx, y)                   # 6x y^2
        np.testing.assert_allclose(gxy.numpy(), [6 * 2.0 * 9.0], rtol=1e-5)

    def test_through_matmul_and_nonlinearity(self):
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(4, 3).astype("float32"))
        w = paddle.to_tensor(rs.randn(3, 2).astype("float32"))
        x.stop_gradient = False
        w.stop_gradient = False
        y = paddle.sum(paddle.tanh(paddle.matmul(x, w)) ** 2)
        (gx,) = paddle.grad(y, x, create_graph=True)
        gnorm = paddle.sum(gx * gx)
        (gw,) = paddle.grad(gnorm, w)

        # finite differences of d||dy/dx||^2 / dw
        def gnorm_np(wv):
            import jax
            import jax.numpy as jnp

            def f(xv):
                return jnp.sum(jnp.tanh(xv @ wv) ** 2)
            g = jax.grad(f)(np.asarray(x.numpy()))
            return float(np.sum(np.asarray(g) ** 2))

        w0 = w.numpy().copy()
        eps = 1e-3
        fd = np.zeros_like(w0)
        for i in range(w0.shape[0]):
            for j in range(w0.shape[1]):
                wp = w0.copy(); wp[i, j] += eps
                wm = w0.copy(); wm[i, j] -= eps
                fd[i, j] = (gnorm_np(wp) - gnorm_np(wm)) / (2 * eps)
        np.testing.assert_allclose(gw.numpy(), fd, rtol=2e-2, atol=2e-3)

    def test_wgan_gp_gradient_penalty(self):
        """Gradient-penalty loss backprops into D's params; check against
        finite differences (the VERDICT round-1 'done' criterion)."""
        paddle.seed(7)
        rs = np.random.RandomState(7)
        D = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 1))
        x = paddle.to_tensor(rs.randn(5, 4).astype("float32"))
        x.stop_gradient = False

        def gp_loss():
            out = D(x)
            (gx,) = paddle.grad(paddle.sum(out), x, create_graph=True)
            norm = paddle.sqrt(paddle.sum(gx * gx, axis=1) + 1e-12)
            return paddle.mean((norm - 1.0) ** 2)

        loss = gp_loss()
        loss.backward()
        params = list(D.parameters())
        analytic = [p.grad.numpy().copy() if p.grad is not None else None
                    for p in params]
        assert any(a is not None and np.abs(a).sum() > 0 for a in analytic)

        # finite-difference check on the first weight matrix
        p0 = params[0]
        base = p0.numpy().copy()
        eps = 1e-3
        idxs = [(0, 0), (1, 3), (3, 7)]
        for (i, j) in idxs:
            for sgn, store in ((1, "plus"), (-1, "minus")):
                pass
            plus = base.copy(); plus[i, j] += eps
            minus = base.copy(); minus[i, j] -= eps
            p0._data = paddle.to_tensor(plus)._data
            lp = float(gp_loss().numpy())
            p0._data = paddle.to_tensor(minus)._data
            lm = float(gp_loss().numpy())
            p0._data = paddle.to_tensor(base)._data
            fd = (lp - lm) / (2 * eps)
            np.testing.assert_allclose(analytic[0][i, j], fd,
                                       rtol=5e-2, atol=1e-4)

    def test_create_graph_false_unchanged(self):
        x = paddle.to_tensor(np.array([2.0], "float32"))
        x.stop_gradient = False
        y = x ** 2
        (g,) = paddle.grad(y, x)
        np.testing.assert_allclose(g.numpy(), [4.0], rtol=1e-6)
        # grads from the plain path are constants
        assert g.stop_gradient

    def test_second_backward_without_create_graph_raises(self):
        x = paddle.to_tensor(np.array([2.0], "float32"))
        x.stop_gradient = False
        y = paddle.sum(x ** 2)
        y.backward()
        with pytest.raises(RuntimeError, match="second time"):
            y.backward()
