"""Ragged paged attention (Pallas) — kernel and engine-path tests.

The kernel (ops/ragged_paged_attn.py) runs under interpret mode on
CPU, so tier-1 exercises the REAL kernel logic token-for-token against
the XLA oracle: per-slot pos/width/block-tables as data, width-masked
scratch writes, and the one-program compile-matrix collapse the
``attn_impl="ragged"`` engine path claims.  Tests marked ``pallas``
involve the kernel; the compiled-Mosaic variant additionally skips
off-TPU (the marker's real-hardware tier).
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.models import GPTModel
from paddle_tpu.serving import Engine


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0)
    m.eval()
    return m


def _engine(model, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("registry", monitor.StatRegistry())
    kw.setdefault("kv_block_size", 8)
    return Engine(model, **kw)


def _prompts(n, lens=(5, 21, 3, 17, 7, 12)):
    rng = np.random.RandomState(7)
    return [rng.randint(0, 128, (lens[i % len(lens)],)).astype(np.int32)
            for i in range(n)]


def _ref(model, prompt, n):
    return model.generate(paddle.to_tensor(prompt[None, :]),
                          max_new_tokens=n).numpy()[0]


def _serve_mixed(model, prompts, max_new=6, greedy_only=False, **kw):
    """Serve a greedy+seeded mix and return the token streams."""
    eng = _engine(model, **kw)
    reqs = []
    for i, p in enumerate(prompts):
        if i % 2 and not greedy_only:
            reqs.append(eng.submit(p, max_new_tokens=max_new,
                                   temperature=0.8, top_p=0.9,
                                   seed=77 + i))
        else:
            reqs.append(eng.submit(p, max_new_tokens=max_new))
    eng.run_until_idle()
    return [r.result(timeout=2).tolist() for r in reqs], eng


# -- kernel unit level ------------------------------------------------

@pytest.mark.pallas
def test_kernel_matches_oracle_gather_math():
    """The kernel's gather -> f32 score -> mask -> softmax -> value
    contraction equals the XLA oracle (``_slot_attn`` over the
    block-table gather) BITWISE on CPU, per slot, for real lanes;
    width-masked lanes (and whole parked width-0 slots) are zeroed."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.ragged_paged_attn import ragged_paged_attention

    rng = np.random.RandomState(0)
    B, W, H, hd = 4, 5, 4, 8
    bs, nb, NB = 8, 6, 20
    q = jnp.asarray(rng.randn(B, W, H, hd).astype(np.float32))
    k_flat = jnp.asarray(rng.randn(NB * bs, H, hd).astype(np.float32))
    v_flat = jnp.asarray(rng.randn(NB * bs, H, hd).astype(np.float32))
    tables = jnp.asarray(rng.randint(0, NB, (B, nb)).astype(np.int32))
    pos = jnp.asarray(np.array([3, 10, 0, 30], np.int32))
    width = jnp.asarray(np.array([1, 5, 0, 3], np.int32))
    out = np.asarray(ragged_paged_attention(
        q, k_flat, v_flat, tables, pos, width, block_size=bs))
    # oracle: the batched _slot_attn math over the gathered rows
    gidx = ((np.asarray(tables) * bs)[:, :, None]
            + np.arange(bs)[None, None, :]).reshape(B, -1)
    k_rows = np.asarray(k_flat)[gidx]
    v_rows = np.asarray(v_flat)[gidx]
    scores = jnp.einsum("bqhd,bkhd->bhqk",
                        jnp.asarray(q, jnp.float32),
                        jnp.asarray(k_rows, jnp.float32)) \
        * (1.0 / math.sqrt(hd))
    L = nb * bs
    visible = (np.arange(L)[None, None, :]
               <= (np.asarray(pos)[:, None]
                   + np.arange(W)[None, :])[:, :, None])
    scores = jnp.where(jnp.asarray(visible)[:, None, :, :], scores,
                       -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    ctx = np.asarray(jnp.einsum("bhqk,bkhd->bqhd", probs,
                                jnp.asarray(v_rows, jnp.float32)))
    for b in range(B):
        w = int(width[b])
        if w:
            np.testing.assert_array_equal(out[b, :w], ctx[b, :w])
        assert np.all(out[b, w:] == 0.0), \
            "width-masked lanes must be zeroed (width is kernel data)"


@pytest.mark.pallas
@pytest.mark.slow
def test_kernel_compiled_lowering_on_tpu():
    """Real-TPU tier: the same kernel compiled through Mosaic (no
    interpret) matches interpret mode.  Skips everywhere but TPU —
    the pallas marker's hardware-gated variant."""
    import jax
    if jax.default_backend() != "tpu":
        pytest.skip("compiled Mosaic lowering needs a TPU backend")
    import jax.numpy as jnp
    from paddle_tpu.ops.ragged_paged_attn import ragged_paged_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 4, 4, 128).astype(np.float32))
    k = jnp.asarray(rng.randn(8 * 16, 4, 128).astype(np.float32))
    v = jnp.asarray(rng.randn(8 * 16, 4, 128).astype(np.float32))
    tables = jnp.asarray(rng.randint(1, 8, (2, 4)).astype(np.int32))
    pos = jnp.asarray(np.array([3, 9], np.int32))
    width = jnp.asarray(np.array([4, 1], np.int32))
    a = ragged_paged_attention(q, k, v, tables, pos, width,
                               block_size=16, interpret=True)
    b = ragged_paged_attention(q, k, v, tables, pos, width,
                               block_size=16, interpret=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


# -- knob validation --------------------------------------------------

def test_attn_impl_validation(tiny_gpt):
    with pytest.raises(ValueError, match="attn_impl"):
        GPTModel(num_layers=1, hidden_size=32, num_heads=2,
                 vocab_size=64, max_position=32, attn_impl="bogus")
    with pytest.raises(ValueError, match="attn_impl"):
        _engine(tiny_gpt, attn_impl="bogus")
    with pytest.raises(ValueError, match="paged"):
        _engine(tiny_gpt, attn_impl="ragged", kv_block_size=None)
    with pytest.raises(ValueError, match="device"):
        _engine(tiny_gpt, attn_impl="ragged", sample_mode="host")
    # the engine inherits the model's knob when not overridden
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0, attn_impl="ragged")
    m.eval()
    eng = _engine(m)
    assert eng.attn_impl == "ragged"
    assert _engine(m, attn_impl="xla").attn_impl == "xla"
    assert _engine(tiny_gpt).attn_impl == "xla"


# -- engine-path parity vs the XLA oracle -----------------------------

@pytest.mark.pallas
@pytest.mark.parametrize("cfg", [
    dict(async_depth=1),
    dict(async_depth=2),
    dict(prefill_chunk=8, async_depth=2),
    dict(spec_k=3, async_depth=2),
    dict(prefill_chunk=8, spec_k=3, async_depth=2),
], ids=["plain-d1", "plain-d2", "chunked-d2", "spec-d2",
        "chunked-spec-d2"])
def test_ragged_parity_vs_xla_oracle(tiny_gpt, cfg):
    """The acceptance criterion: greedy AND seeded streams under
    ``attn_impl="ragged"`` (the Pallas kernel, interpret mode) are
    token-identical to the XLA oracle across paged plain / chunked /
    spec dispatch shapes at async depth 2 — and the greedy streams
    equal per-request ``generate()``.

    Chunked configs run the concurrent mix ALL-GREEDY plus a
    separate seeded single-request parity check: ragged chunk lanes
    pipeline the final chunk ahead of the first decode tick, so a
    neighbor finishes a tick later than under the XLA arm, and under
    the repo's rbg PRNG a CONCURRENT seeded draw depends on that
    co-scheduling (the PR10-documented property — XLA depth1 vs
    depth2 seeded chunked streams diverge for exactly the same
    reason).  With co-scheduling arm-stable (no chunking, or a
    single request), seeded streams are bitwise arm-identical."""
    prompts = _prompts(4)
    chunked = "prefill_chunk" in cfg
    if chunked:
        xla, _ = _serve_mixed(tiny_gpt, prompts, greedy_only=True,
                              attn_impl="xla", **cfg)
        rag, eng = _serve_mixed(tiny_gpt, prompts, greedy_only=True,
                                attn_impl="ragged", **cfg)
        seeded = {}
        for impl in ("xla", "ragged"):
            e2 = _engine(tiny_gpt, attn_impl=impl, **cfg)
            r = e2.submit(prompts[1], max_new_tokens=10,
                          temperature=0.8, top_p=0.9, seed=42)
            e2.run_until_idle()
            seeded[impl] = r.result(timeout=2).tolist()
        assert seeded["xla"] == seeded["ragged"]
    else:
        xla, _ = _serve_mixed(tiny_gpt, prompts, attn_impl="xla",
                              **cfg)
        rag, eng = _serve_mixed(tiny_gpt, prompts,
                                attn_impl="ragged", **cfg)
    assert xla == rag
    greedy_lanes = range(4) if chunked else (0, 2)
    for i in greedy_lanes:
        assert rag[i] == _ref(tiny_gpt, prompts[i], 6).tolist()
    # refcount hygiene: the ragged path's width-masked writes never
    # leak a block reference
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()
    assert eng.block_pool.in_use() == 0


@pytest.mark.pallas
@pytest.mark.parametrize("cfg", [
    dict(),
    dict(prefill_chunk=8, spec_k=3),
], ids=["plain", "chunked-spec"])
def test_ragged_preempt_resume_parity(tiny_gpt, cfg):
    """Preemption-resume under the ragged kernel: the preempted
    stream's continuation is token-identical to an uninterrupted
    ``generate()`` (greedy), across the unified dispatch shapes."""
    eng = _engine(tiny_gpt, num_slots=1, attn_impl="ragged",
                  async_depth=2, **cfg)
    p_low, p_high = _prompts(2)
    low = eng.submit(p_low, max_new_tokens=12, priority=0)
    for _ in range(5):
        eng.step()
    assert not low.done()
    high = eng.submit(p_high, max_new_tokens=4, priority=5)
    eng.run_until_idle()
    np.testing.assert_array_equal(high.result(timeout=2),
                                  _ref(tiny_gpt, p_high, 4))
    np.testing.assert_array_equal(low.result(timeout=2),
                                  _ref(tiny_gpt, p_low, 12))
    assert low.preemptions >= 1
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()
    assert eng.block_pool.in_use() == 0


@pytest.mark.pallas
def test_ragged_preempt_seeded_stream_unchanged(tiny_gpt):
    """A seeded stream across a ragged-path preemption equals the
    uninterrupted run: the device key folds the emitted-token
    counter, and the kernel path preserves it across the resume."""
    p_low, p_high = _prompts(2)
    un = _engine(tiny_gpt, num_slots=1, attn_impl="ragged")
    r0 = un.submit(p_low, max_new_tokens=12, temperature=0.8,
                   top_p=0.9, seed=5)
    un.run_until_idle()
    eng = _engine(tiny_gpt, num_slots=1, attn_impl="ragged")
    low = eng.submit(p_low, max_new_tokens=12, temperature=0.8,
                     top_p=0.9, seed=5)
    for _ in range(5):
        eng.step()
    eng.submit(p_high, max_new_tokens=4, priority=5)
    eng.run_until_idle()
    assert low.preemptions >= 1
    assert low.result(timeout=2).tolist() == \
        r0.result(timeout=2).tolist()


# -- compile-matrix collapse (the perf_opt claim) ---------------------

@pytest.mark.pallas
def test_ragged_compile_matrix_collapse():
    """Satellite regression: a mixed workload (chunked long prompts +
    short decode + spec_k=3, paged, depth2) compiles STRICTLY FEWER
    programs under ``attn_impl="ragged"`` than under the XLA path —
    the (chunk shape, spec_k) matrix collapses to exactly ONE
    ``ragged_window`` program — and a second traffic wave compiles
    NOTHING on either arm (no steady-state thrash)."""
    prompts = _prompts(6)

    def wave(eng):
        reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        for r in reqs:
            r.result(timeout=2)

    counts = {}
    for impl in ("xla", "ragged"):
        paddle.seed(0)
        m = GPTModel.from_config("tiny", dropout=0.0)  # fresh caches
        m.eval()
        reg = monitor.StatRegistry()
        eng = Engine(m, num_slots=4, max_seq_len=48, registry=reg,
                     kv_block_size=8, prefill_chunk=8, spec_k=3,
                     async_depth=2, attn_impl=impl)
        wave(eng)
        c1 = reg.get("serving.compiles_total").value
        wave(eng)
        c2 = reg.get("serving.compiles_total").value
        assert c2 == c1, \
            f"{impl}: second wave recompiled ({c1} -> {c2})"
        counts[impl] = c1
        if impl == "ragged":
            # exactly one program serves decode + spec-verify +
            # chunk-prefill — the collapse, not just a reduction
            assert c1 == 1
            assert len(m._ragged_window_fn_cache) == 1
    assert counts["ragged"] < counts["xla"]


@pytest.mark.pallas
def test_ragged_one_program_however_traffic_varies(tiny_gpt):
    """However prompt lengths, sampling params, and request mixes
    vary, a ragged engine config resolves to ONE compiled window
    program (widths are data, not shape)."""
    eng = _engine(tiny_gpt, prefill_chunk=8, spec_k=3,
                  attn_impl="ragged")
    before = len(tiny_gpt._ragged_window_fn_cache)
    for p in _prompts(6):
        eng.submit(p, max_new_tokens=4)
    eng.submit(_prompts(1)[0], max_new_tokens=4, temperature=0.7,
               top_k=20, seed=3)
    eng.run_until_idle()
    added = len(tiny_gpt._ragged_window_fn_cache) - before
    assert added <= 1  # one NEW program for this (B, W, pool) config


# -- epilogue / payload / surfaces ------------------------------------

@pytest.mark.pallas
def test_ragged_spec_d2h_payload_stays_97_bytes(tiny_gpt):
    """The acceptance scan folds into the ragged epilogue, so a spec
    tick still downloads picks [B, W] + n_acc + n_emit + the packed
    done mask = 97 bytes at B=4, spec_k=3 — the same steady state as
    the fused XLA spec path, with no separate acceptance dispatch."""
    eng = _engine(tiny_gpt, spec_k=3, attn_impl="ragged",
                  async_depth=2)
    reqs = [eng.submit(p, max_new_tokens=6) for p in _prompts(4)]
    eng.run_until_idle()
    for r in reqs:
        r.result(timeout=2)
    # picks 4*4*4 + n_acc 4*4 + n_emit 4*4 + done 1 = 97
    assert eng.registry.get("serving.d2h_bytes_per_tick").value == 97


@pytest.mark.pallas
def test_ragged_healthz_debug_and_trace_span(tiny_gpt):
    """/healthz and /debug/requests report the kernel selection, and
    the trace carries ``decode.ragged`` spans (never the XLA path's
    ``decode.dispatch``) so traces distinguish kernel dispatches."""
    from paddle_tpu.serving.httpd import _Handler

    eng = _engine(tiny_gpt, prefill_chunk=8, attn_impl="ragged")
    r = eng.submit(_prompts(1)[0], max_new_tokens=4)
    eng.run_until_idle()
    r.result(timeout=2)
    assert eng.debug_requests()["engine"]["attn_impl"] == "ragged"

    h = object.__new__(_Handler)
    h.engine = eng
    h.path = "/healthz"
    sent = {}

    def _send(code, payload, ctype="application/json", headers=None):
        sent["resp"] = (code, payload)

    h._send = _send
    import json as _json
    h._send_json = lambda code, obj: _send(code, _json.dumps(obj))
    h.do_GET()
    code, body = sent["resp"]
    assert code == 200
    assert _json.loads(body)["attn_impl"] == "ragged"

    names = {ev.get("name")
             for ev in eng.chrome_trace()["traceEvents"]}
    assert "decode.ragged" in names
    assert "decode.dispatch" not in names


def test_ragged_step_failure_recovers(tiny_gpt):
    """Step-failure recovery under the ragged path: waiters unblock
    loudly, refcounts rebuild to zero, and the engine serves correct
    streams afterwards."""
    eng = _engine(tiny_gpt, num_slots=2, attn_impl="ragged")
    prompts = _prompts(2)
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.step()
    eng.step()

    def boom(*a, **kw):
        raise RuntimeError("synthetic ragged dispatch failure")

    eng._ragged_fn = boom
    with pytest.raises(RuntimeError):
        eng.step()
    for r in reqs:
        with pytest.raises(RuntimeError, match="engine step failed"):
            r.result(timeout=2)
    assert eng.scheduler.occupancy() == 0
    assert eng.block_pool.in_use() == 0
    assert all(eng.block_pool.refcount(b) == 0
               for b in range(eng.block_pool.num_blocks))
    eng._ragged_fn = None
    r2 = eng.submit(prompts[0], max_new_tokens=6)
    eng.run_until_idle()
    assert r2.result(timeout=2).tolist() == \
        _ref(tiny_gpt, prompts[0], 6).tolist()
