"""Ragged paged attention (Pallas) — kernel and engine-path tests.

The kernel (ops/ragged_paged_attn.py) runs under interpret mode on
CPU, so tier-1 exercises the REAL kernel logic token-for-token against
the XLA oracle: per-slot pos/width/block-tables as data, width-masked
scratch writes, and the one-program compile-matrix collapse the
``attn_impl="ragged"`` engine path claims.  Tests marked ``pallas``
involve the kernel; the compiled-Mosaic variant additionally skips
off-TPU (the marker's real-hardware tier).

NUMERICS CONTRACT (two kernel bodies):

* ``attn_impl="ragged"`` — the default STREAMING body, a flash-style
  online-softmax loop over the slot's live blocks.  Online softmax
  reorders float summation, so the kernel is ALLCLOSE to the oracle
  (not bitwise); end-to-end, GREEDY streams are asserted
  token-identical to the XLA arm across the full layout matrix and
  seeded streams are asserted deterministic (same seed, same stream).
* ``attn_impl="ragged_gather"`` — the materialize-the-row A/B
  reference: BITWISE-equal to the oracle on CPU, greedy AND seeded
  streams token-identical to the XLA arm.

Tests marked ``longctx`` cover prompts spanning many KV blocks — the
streaming kernel's O(block_size x window) working-set claim; the
small-shape twins run in tier-1, the multi-thousand-token leg is
additionally marked slow.
"""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.models import GPTModel
from paddle_tpu.serving import Engine


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0)
    m.eval()
    return m


def _engine(model, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("registry", monitor.StatRegistry())
    kw.setdefault("kv_block_size", 8)
    return Engine(model, **kw)


def _prompts(n, lens=(5, 21, 3, 17, 7, 12)):
    rng = np.random.RandomState(7)
    return [rng.randint(0, 128, (lens[i % len(lens)],)).astype(np.int32)
            for i in range(n)]


def _ref(model, prompt, n):
    return model.generate(paddle.to_tensor(prompt[None, :]),
                          max_new_tokens=n).numpy()[0]


def _serve_mixed(model, prompts, max_new=6, greedy_only=False, **kw):
    """Serve a greedy+seeded mix and return the token streams."""
    eng = _engine(model, **kw)
    reqs = []
    for i, p in enumerate(prompts):
        if i % 2 and not greedy_only:
            reqs.append(eng.submit(p, max_new_tokens=max_new,
                                   temperature=0.8, top_p=0.9,
                                   seed=77 + i))
        else:
            reqs.append(eng.submit(p, max_new_tokens=max_new))
    eng.run_until_idle()
    return [r.result(timeout=2).tolist() for r in reqs], eng


# -- kernel unit level ------------------------------------------------

def _kernel_oracle(q, k_flat, v_flat, tables, pos, width, bs):
    """The batched _slot_attn math over the gathered rows."""
    import jax
    import jax.numpy as jnp
    B, W, H, hd = q.shape
    nb = tables.shape[1]
    gidx = ((np.asarray(tables) * bs)[:, :, None]
            + np.arange(bs)[None, None, :]).reshape(B, -1)
    k_rows = np.asarray(k_flat)[gidx]
    v_rows = np.asarray(v_flat)[gidx]
    scores = jnp.einsum("bqhd,bkhd->bhqk",
                        jnp.asarray(q, jnp.float32),
                        jnp.asarray(k_rows, jnp.float32)) \
        * (1.0 / math.sqrt(hd))
    L = nb * bs
    visible = (np.arange(L)[None, None, :]
               <= (np.asarray(pos)[:, None]
                   + np.arange(W)[None, :])[:, :, None])
    scores = jnp.where(jnp.asarray(visible)[:, None, :, :], scores,
                       -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return np.asarray(jnp.einsum("bhqk,bkhd->bqhd", probs,
                                 jnp.asarray(v_rows, jnp.float32)))


@pytest.mark.pallas
@pytest.mark.parametrize("variant", ["stream", "gather"])
def test_kernel_matches_oracle(variant):
    """Per slot, for real lanes, against the XLA oracle math
    (``_slot_attn`` over the block-table gather): the GATHER body is
    BITWISE-equal on CPU; the STREAMING body's online softmax is
    allclose (block-sequential accumulation reorders the float sums).
    Width-masked lanes (and whole parked width-0 slots) are zeroed
    EXACTLY under both bodies."""
    import jax.numpy as jnp
    from paddle_tpu.ops.ragged_paged_attn import ragged_paged_attention

    rng = np.random.RandomState(0)
    B, W, H, hd = 4, 5, 4, 8
    bs, nb, NB = 8, 6, 20
    q = jnp.asarray(rng.randn(B, W, H, hd).astype(np.float32))
    k_flat = jnp.asarray(rng.randn(NB * bs, H, hd).astype(np.float32))
    v_flat = jnp.asarray(rng.randn(NB * bs, H, hd).astype(np.float32))
    tables = jnp.asarray(rng.randint(0, NB, (B, nb)).astype(np.int32))
    pos = jnp.asarray(np.array([3, 10, 0, 30], np.int32))
    width = jnp.asarray(np.array([1, 5, 0, 3], np.int32))
    out = np.asarray(ragged_paged_attention(
        q, k_flat, v_flat, tables, pos, width, block_size=bs,
        variant=variant))
    ctx = _kernel_oracle(q, k_flat, v_flat, tables, pos, width, bs)
    for b in range(B):
        w = int(width[b])
        if w:
            if variant == "gather":
                np.testing.assert_array_equal(out[b, :w], ctx[b, :w])
            else:
                np.testing.assert_allclose(out[b, :w], ctx[b, :w],
                                           rtol=2e-5, atol=2e-6)
        assert np.all(out[b, w:] == 0.0), \
            "width-masked lanes must be zeroed (width is kernel data)"


@pytest.mark.pallas
def test_kernel_variant_validation():
    import jax.numpy as jnp
    from paddle_tpu.ops.ragged_paged_attn import (
        kernel_working_set_bytes, ragged_paged_attention)

    z = jnp.zeros((1, 1, 1, 4), jnp.float32)
    with pytest.raises(ValueError, match="variant"):
        ragged_paged_attention(
            z, jnp.zeros((8, 1, 4)), jnp.zeros((8, 1, 4)),
            jnp.zeros((1, 1), jnp.int32), jnp.zeros(1, jnp.int32),
            jnp.ones(1, jnp.int32), block_size=8, variant="bogus")
    with pytest.raises(ValueError, match="variant"):
        kernel_working_set_bytes(variant="bogus", block_size=8,
                                 blocks_per_slot=4, width=4,
                                 num_heads=2, head_dim=8)
    # the analytic VMEM proxy: streaming is FLAT in context length,
    # gather grows linearly with it
    args = dict(block_size=8, width=4, num_heads=2, head_dim=8)
    s4 = kernel_working_set_bytes(variant="stream",
                                  blocks_per_slot=4, **args)
    s64 = kernel_working_set_bytes(variant="stream",
                                   blocks_per_slot=64, **args)
    g4 = kernel_working_set_bytes(variant="gather",
                                  blocks_per_slot=4, **args)
    g8 = kernel_working_set_bytes(variant="gather",
                                  blocks_per_slot=8, **args)
    g64 = kernel_working_set_bytes(variant="gather",
                                   blocks_per_slot=64, **args)
    assert s4 == s64, "streaming working set must not grow with blocks"
    assert g64 - g4 == 15 * (g8 - g4), "gather grows linearly"
    assert g64 > 10 * s64


@pytest.mark.pallas
@pytest.mark.longctx
def test_kernel_stream_allclose_long_tables():
    """Long-context kernel twin (prompts >= 8x block_size): a table
    of MANY live blocks, decode + verify + chunk widths mixed, int8
    per-block scales included — the streaming body stays allclose to
    the oracle while walking only the live horizon."""
    import jax.numpy as jnp
    from paddle_tpu.ops.ragged_paged_attn import ragged_paged_attention

    rng = np.random.RandomState(1)
    B, W, H, hd = 3, 5, 4, 8
    bs, nb, NB = 8, 16, 48                    # up to 128 ctx tokens
    q = jnp.asarray(rng.randn(B, W, H, hd).astype(np.float32))
    k_flat = jnp.asarray(rng.randn(NB * bs, H, hd).astype(np.float32))
    v_flat = jnp.asarray(rng.randn(NB * bs, H, hd).astype(np.float32))
    tables = jnp.asarray(rng.randint(0, NB, (B, nb)).astype(np.int32))
    pos = jnp.asarray(np.array([100, 127 - 5, 64], np.int32))
    width = jnp.asarray(np.array([1, 5, 3], np.int32))
    out = np.asarray(ragged_paged_attention(
        q, k_flat, v_flat, tables, pos, width, block_size=bs,
        variant="stream"))
    ctx = _kernel_oracle(q, k_flat, v_flat, tables, pos, width, bs)
    for b in range(B):
        w = int(width[b])
        np.testing.assert_allclose(out[b, :w], ctx[b, :w],
                                   rtol=2e-5, atol=2e-6)
        assert np.all(out[b, w:] == 0.0)
    # int8 codes + per-block scales: stream and gather dequantize the
    # same blocks, so they agree to float-reassociation tolerance at
    # long context too
    ck = jnp.asarray(rng.randint(-127, 128, (NB * bs, H, hd))
                     .astype(np.int8))
    cv = jnp.asarray(rng.randint(-127, 128, (NB * bs, H, hd))
                     .astype(np.int8))
    ks = jnp.asarray(rng.uniform(0.01, 0.05, (NB, H))
                     .astype(np.float32))
    vs = jnp.asarray(rng.uniform(0.01, 0.05, (NB, H))
                     .astype(np.float32))
    sq = ragged_paged_attention(q, ck, cv, tables, pos, width,
                                block_size=bs, k_scale=ks, v_scale=vs,
                                variant="stream")
    gq = ragged_paged_attention(q, ck, cv, tables, pos, width,
                                block_size=bs, k_scale=ks, v_scale=vs,
                                variant="gather")
    np.testing.assert_allclose(np.asarray(sq), np.asarray(gq),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.pallas
@pytest.mark.slow
@pytest.mark.parametrize("variant", ["stream", "gather"])
def test_kernel_compiled_lowering_on_tpu(variant):
    """Real-TPU tier: the same kernel compiled through Mosaic (no
    interpret) matches interpret mode — for BOTH bodies, streaming
    online-softmax included.  Skips everywhere but TPU — the pallas
    marker's hardware-gated variant."""
    import jax
    if jax.default_backend() != "tpu":
        pytest.skip("compiled Mosaic lowering needs a TPU backend")
    import jax.numpy as jnp
    from paddle_tpu.ops.ragged_paged_attn import ragged_paged_attention

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(2, 4, 4, 128).astype(np.float32))
    k = jnp.asarray(rng.randn(8 * 16, 4, 128).astype(np.float32))
    v = jnp.asarray(rng.randn(8 * 16, 4, 128).astype(np.float32))
    tables = jnp.asarray(rng.randint(1, 8, (2, 4)).astype(np.int32))
    pos = jnp.asarray(np.array([3, 9], np.int32))
    width = jnp.asarray(np.array([4, 1], np.int32))
    a = ragged_paged_attention(q, k, v, tables, pos, width,
                               block_size=16, interpret=True,
                               variant=variant)
    b = ragged_paged_attention(q, k, v, tables, pos, width,
                               block_size=16, interpret=False,
                               variant=variant)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


# -- knob validation --------------------------------------------------

def test_attn_impl_validation(tiny_gpt):
    with pytest.raises(ValueError, match="attn_impl"):
        GPTModel(num_layers=1, hidden_size=32, num_heads=2,
                 vocab_size=64, max_position=32, attn_impl="bogus")
    with pytest.raises(ValueError, match="attn_impl"):
        _engine(tiny_gpt, attn_impl="bogus")
    with pytest.raises(ValueError, match="paged"):
        _engine(tiny_gpt, attn_impl="ragged", kv_block_size=None)
    with pytest.raises(ValueError, match="device"):
        _engine(tiny_gpt, attn_impl="ragged", sample_mode="host")
    # the gather A/B reference shares the ragged constraints
    with pytest.raises(ValueError, match="paged"):
        _engine(tiny_gpt, attn_impl="ragged_gather",
                kv_block_size=None)
    with pytest.raises(ValueError, match="device"):
        _engine(tiny_gpt, attn_impl="ragged_gather",
                sample_mode="host")
    assert _engine(tiny_gpt,
                   attn_impl="ragged_gather").attn_impl \
        == "ragged_gather"
    # the engine inherits the model's knob when not overridden
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0, attn_impl="ragged")
    m.eval()
    eng = _engine(m)
    assert eng.attn_impl == "ragged"
    assert _engine(m, attn_impl="xla").attn_impl == "xla"
    assert _engine(tiny_gpt).attn_impl == "xla"


# -- engine-path parity vs the XLA oracle -----------------------------

@pytest.mark.pallas
@pytest.mark.parametrize("cfg", [
    dict(async_depth=1),
    dict(async_depth=2),
    dict(prefill_chunk=8, async_depth=2),
    dict(spec_k=3, async_depth=2),
    dict(prefill_chunk=8, spec_k=3, async_depth=2),
    dict(kv_dtype="int8", async_depth=2),
    dict(kv_dtype="int8", prefill_chunk=8, spec_k=3, async_depth=2),
], ids=["plain-d1", "plain-d2", "chunked-d2", "spec-d2",
        "chunked-spec-d2", "kvint8-d2", "kvint8-chunked-spec-d2"])
def test_ragged_parity_vs_xla_oracle(tiny_gpt, cfg):
    """THE acceptance criterion, full layout matrix with the
    STREAMING kernel as the ``attn_impl="ragged"`` default: GREEDY
    streams are token-identical to the XLA oracle across paged plain
    / chunked / spec / int8-KV dispatch shapes at async depth 1 and 2
    — and equal per-request ``generate()``.  (Seeded-stream
    guarantees: determinism under streaming —
    ``test_ragged_stream_seeded_deterministic`` — and bitwise arm
    identity under the gather body —
    ``test_ragged_gather_parity_vs_xla_oracle``.)"""
    prompts = _prompts(4)
    xla, _ = _serve_mixed(tiny_gpt, prompts, greedy_only=True,
                          attn_impl="xla", **cfg)
    rag, eng = _serve_mixed(tiny_gpt, prompts, greedy_only=True,
                            attn_impl="ragged", **cfg)
    assert xla == rag
    if cfg.get("kv_dtype") is None:
        # int8 engines legitimately diverge from the fp generate()
        # oracle (quantized cache); fp engines must not
        for i in range(4):
            assert rag[i] == _ref(tiny_gpt, prompts[i], 6).tolist()
    # refcount hygiene: the ragged path's width-masked writes never
    # leak a block reference
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()
    assert eng.block_pool.in_use() == 0


@pytest.mark.pallas
@pytest.mark.parametrize("cfg", [
    dict(async_depth=2),
    dict(prefill_chunk=8, spec_k=3, async_depth=2),
], ids=["plain-d2", "chunked-spec-d2"])
def test_ragged_gather_parity_vs_xla_oracle(tiny_gpt, cfg):
    """The A/B reference keeps the ORIGINAL contract: greedy AND
    seeded streams under ``attn_impl="ragged_gather"`` are
    token-identical to the XLA oracle (bitwise kernel math).

    Chunked configs run the concurrent mix ALL-GREEDY plus a
    separate seeded single-request parity check: ragged chunk lanes
    pipeline the final chunk ahead of the first decode tick, so a
    neighbor finishes a tick later than under the XLA arm, and under
    the repo's rbg PRNG a CONCURRENT seeded draw depends on that
    co-scheduling (the PR10-documented property — XLA depth1 vs
    depth2 seeded chunked streams diverge for exactly the same
    reason).  With co-scheduling arm-stable (no chunking, or a
    single request), seeded streams are bitwise arm-identical."""
    prompts = _prompts(4)
    chunked = "prefill_chunk" in cfg
    if chunked:
        xla, _ = _serve_mixed(tiny_gpt, prompts, greedy_only=True,
                              attn_impl="xla", **cfg)
        rag, eng = _serve_mixed(tiny_gpt, prompts, greedy_only=True,
                                attn_impl="ragged_gather", **cfg)
        seeded = {}
        for impl in ("xla", "ragged_gather"):
            e2 = _engine(tiny_gpt, attn_impl=impl, **cfg)
            r = e2.submit(prompts[1], max_new_tokens=10,
                          temperature=0.8, top_p=0.9, seed=42)
            e2.run_until_idle()
            seeded[impl] = r.result(timeout=2).tolist()
        assert seeded["xla"] == seeded["ragged_gather"]
    else:
        xla, _ = _serve_mixed(tiny_gpt, prompts, attn_impl="xla",
                              **cfg)
        rag, eng = _serve_mixed(tiny_gpt, prompts,
                                attn_impl="ragged_gather", **cfg)
    assert xla == rag
    greedy_lanes = range(4) if chunked else (0, 2)
    for i in greedy_lanes:
        assert rag[i] == _ref(tiny_gpt, prompts[i], 6).tolist()
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()
    assert eng.block_pool.in_use() == 0


@pytest.mark.pallas
def test_ragged_stream_seeded_deterministic(tiny_gpt):
    """The streaming kernel's seeded contract: same seed => same
    stream, run-for-run (online softmax reorders float summation, so
    bitwise-vs-XLA is the gather body's guarantee, not this one —
    but a seeded stream must still be reproducible)."""
    p = _prompts(1)[0]
    runs = []
    for _ in range(2):
        eng = _engine(tiny_gpt, attn_impl="ragged", spec_k=2,
                      async_depth=2)
        r = eng.submit(p, max_new_tokens=10, temperature=0.8,
                       top_p=0.9, seed=42)
        eng.run_until_idle()
        runs.append(r.result(timeout=2).tolist())
    assert runs[0] == runs[1]


@pytest.mark.pallas
@pytest.mark.parametrize("cfg", [
    dict(),
    dict(prefill_chunk=8, spec_k=3),
], ids=["plain", "chunked-spec"])
def test_ragged_preempt_resume_parity(tiny_gpt, cfg):
    """Preemption-resume under the ragged kernel: the preempted
    stream's continuation is token-identical to an uninterrupted
    ``generate()`` (greedy), across the unified dispatch shapes."""
    eng = _engine(tiny_gpt, num_slots=1, attn_impl="ragged",
                  async_depth=2, **cfg)
    p_low, p_high = _prompts(2)
    low = eng.submit(p_low, max_new_tokens=12, priority=0)
    for _ in range(5):
        eng.step()
    assert not low.done()
    high = eng.submit(p_high, max_new_tokens=4, priority=5)
    eng.run_until_idle()
    np.testing.assert_array_equal(high.result(timeout=2),
                                  _ref(tiny_gpt, p_high, 4))
    np.testing.assert_array_equal(low.result(timeout=2),
                                  _ref(tiny_gpt, p_low, 12))
    assert low.preemptions >= 1
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()
    assert eng.block_pool.in_use() == 0


@pytest.mark.pallas
def test_ragged_preempt_seeded_stream_unchanged(tiny_gpt):
    """A seeded stream across a ragged-path preemption equals the
    uninterrupted run: the device key folds the emitted-token
    counter, and the kernel path preserves it across the resume."""
    p_low, p_high = _prompts(2)
    un = _engine(tiny_gpt, num_slots=1, attn_impl="ragged")
    r0 = un.submit(p_low, max_new_tokens=12, temperature=0.8,
                   top_p=0.9, seed=5)
    un.run_until_idle()
    eng = _engine(tiny_gpt, num_slots=1, attn_impl="ragged")
    low = eng.submit(p_low, max_new_tokens=12, temperature=0.8,
                     top_p=0.9, seed=5)
    for _ in range(5):
        eng.step()
    eng.submit(p_high, max_new_tokens=4, priority=5)
    eng.run_until_idle()
    assert low.preemptions >= 1
    assert low.result(timeout=2).tolist() == \
        r0.result(timeout=2).tolist()


# -- compile-matrix collapse (the perf_opt claim) ---------------------

@pytest.mark.pallas
def test_ragged_compile_matrix_collapse():
    """Satellite regression: a mixed workload (chunked long prompts +
    short decode + spec_k=3, paged, depth2) compiles STRICTLY FEWER
    programs under ``attn_impl="ragged"`` than under the XLA path —
    the (chunk shape, spec_k) matrix collapses to exactly ONE
    ``ragged_window`` program — and a second traffic wave compiles
    NOTHING on either arm (no steady-state thrash)."""
    prompts = _prompts(6)

    def wave(eng):
        reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run_until_idle()
        for r in reqs:
            r.result(timeout=2)

    counts = {}
    for impl in ("xla", "ragged"):
        paddle.seed(0)
        m = GPTModel.from_config("tiny", dropout=0.0)  # fresh caches
        m.eval()
        reg = monitor.StatRegistry()
        eng = Engine(m, num_slots=4, max_seq_len=48, registry=reg,
                     kv_block_size=8, prefill_chunk=8, spec_k=3,
                     async_depth=2, attn_impl=impl)
        wave(eng)
        c1 = reg.get("serving.compiles_total").value
        wave(eng)
        c2 = reg.get("serving.compiles_total").value
        assert c2 == c1, \
            f"{impl}: second wave recompiled ({c1} -> {c2})"
        counts[impl] = c1
        if impl == "ragged":
            # exactly one program serves decode + spec-verify +
            # chunk-prefill — the collapse, not just a reduction
            assert c1 == 1
            assert len(m._ragged_window_fn_cache) == 1
    assert counts["ragged"] < counts["xla"]


@pytest.mark.pallas
def test_ragged_one_program_however_traffic_varies(tiny_gpt):
    """However prompt lengths, sampling params, and request mixes
    vary, a ragged engine config resolves to ONE compiled window
    program (widths are data, not shape)."""
    eng = _engine(tiny_gpt, prefill_chunk=8, spec_k=3,
                  attn_impl="ragged")
    before = len(tiny_gpt._ragged_window_fn_cache)
    for p in _prompts(6):
        eng.submit(p, max_new_tokens=4)
    eng.submit(_prompts(1)[0], max_new_tokens=4, temperature=0.7,
               top_k=20, seed=3)
    eng.run_until_idle()
    added = len(tiny_gpt._ragged_window_fn_cache) - before
    assert added <= 1  # one NEW program for this (B, W, pool) config


# -- epilogue / payload / surfaces ------------------------------------

@pytest.mark.pallas
def test_ragged_spec_d2h_payload_stays_97_bytes(tiny_gpt):
    """The acceptance scan folds into the ragged epilogue, so a spec
    tick still downloads picks [B, W] + n_acc + n_emit + the packed
    done mask = 97 bytes at B=4, spec_k=3 — the same steady state as
    the fused XLA spec path, with no separate acceptance dispatch."""
    eng = _engine(tiny_gpt, spec_k=3, attn_impl="ragged",
                  async_depth=2)
    reqs = [eng.submit(p, max_new_tokens=6) for p in _prompts(4)]
    eng.run_until_idle()
    for r in reqs:
        r.result(timeout=2)
    # picks 4*4*4 + n_acc 4*4 + n_emit 4*4 + done 1 = 97
    assert eng.registry.get("serving.d2h_bytes_per_tick").value == 97


@pytest.mark.pallas
def test_ragged_healthz_debug_and_trace_span(tiny_gpt):
    """/healthz and /debug/requests report the kernel selection AND
    the max observed context length, the trace carries
    ``decode.ragged_stream`` spans (never the XLA path's
    ``decode.dispatch``, nor the gather body's ``decode.ragged``) so
    traces distinguish kernel dispatches, and the per-tick block-walk
    gauge is populated."""
    from paddle_tpu.serving.httpd import _Handler

    eng = _engine(tiny_gpt, prefill_chunk=8, attn_impl="ragged")
    p = _prompts(1)[0]
    r = eng.submit(p, max_new_tokens=4)
    eng.run_until_idle()
    r.result(timeout=2)
    dbg = eng.debug_requests()["engine"]
    assert dbg["attn_impl"] == "ragged"
    assert dbg["max_context_len"] == len(p) + 4

    h = object.__new__(_Handler)
    h.engine = eng
    h.path = "/healthz"
    sent = {}

    def _send(code, payload, ctype="application/json", headers=None):
        sent["resp"] = (code, payload)

    h._send = _send
    import json as _json
    h._send_json = lambda code, obj: _send(code, _json.dumps(obj))
    h.do_GET()
    code, body = sent["resp"]
    assert code == 200
    health = _json.loads(body)
    assert health["attn_impl"] == "ragged"
    assert health["max_context_len"] == len(p) + 4

    names = {ev.get("name")
             for ev in eng.chrome_trace()["traceEvents"]}
    assert "decode.ragged_stream" in names
    assert "decode.ragged" not in names
    assert "decode.dispatch" not in names
    # block-walk attribution: the last dispatch walked >= 1 block
    assert eng.registry.get(
        "serving.kv_blocks_walked_per_tick").value >= 1


@pytest.mark.pallas
def test_ragged_gather_trace_span_and_walk_gauge(tiny_gpt):
    """The A/B arm keeps its own span name (``decode.ragged``) and
    always walks the FULL per-slot table — its walk gauge reads
    lanes x blocks_per_slot where the streaming arm's reads the live
    horizon, which is the per-tick cost the A/B exists to show."""
    streams = {}
    for impl in ("ragged", "ragged_gather"):
        eng = _engine(tiny_gpt, num_slots=2, attn_impl=impl)
        r = eng.submit(_prompts(1)[0], max_new_tokens=4)
        eng.run_until_idle()
        streams[impl] = r.result(timeout=2).tolist()
        names = {ev.get("name")
                 for ev in eng.chrome_trace()["traceEvents"]}
        walked = eng.registry.get(
            "serving.kv_blocks_walked_per_tick").value
        if impl == "ragged_gather":
            assert "decode.ragged" in names
            assert "decode.ragged_stream" not in names
            # one live lane on the final tick, full table walked
            assert walked == eng._bps
        else:
            assert "decode.ragged_stream" in names
            assert walked < eng._bps  # a 5..9-token stream's horizon
    # A/B serves the same greedy tokens
    assert streams["ragged"] == streams["ragged_gather"]


@pytest.mark.pallas
@pytest.mark.router
def test_router_probe_copies_attn_impl_signal(tiny_gpt):
    """The router prober copies ``attn_impl`` and
    ``max_context_len`` into the replica's registry signals like it
    does ``kv_dtype`` — the fleet view can tell which kernel body
    each replica serves and its long-context exposure."""
    from paddle_tpu.serving import (InProcessReplica, Router,
                                    RouterPolicy)

    eng = _engine(tiny_gpt, attn_impl="ragged")
    r = eng.submit(_prompts(1)[0], max_new_tokens=3)
    eng.run_until_idle()
    r.result(timeout=2)
    rep_client = InProcessReplica("r0", eng)
    probe = rep_client.probe()
    assert probe["attn_impl"] == "ragged"
    assert probe["max_context_len"] > 0
    router = Router({"r0": rep_client},
                    policy=RouterPolicy(seed=0), kv_block_size=8,
                    registry=monitor.StatRegistry())
    router.probe_once()
    rep = router._reps()[0]
    assert rep.signals["attn_impl"] == "ragged"
    assert rep.signals["max_context_len"] == probe["max_context_len"]


# -- long-context serving (the streaming kernel's reason to exist) ----

@pytest.fixture(scope="module")
def long_gpt():
    """The tiny config with a raised context ceiling — long-context
    engines need max_position above the tiny default of 64."""
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0, max_position=256)
    m.eval()
    return m


def _long_prompt(n, seed=3):
    rng = np.random.RandomState(seed)
    return rng.randint(0, 128, (n,)).astype(np.int32)


@pytest.mark.pallas
@pytest.mark.longctx
@pytest.mark.parametrize("cfg", [
    dict(),
    dict(prefill_chunk=8, async_depth=2),
    dict(kv_dtype="int8", prefill_chunk=8),
], ids=["plain", "chunked-d2", "kvint8-chunked"])
def test_longctx_greedy_identity(long_gpt, cfg):
    """Tier-1 long-context twin: a prompt spanning MANY KV blocks
    (>= 8x block_size) decodes greedily token-identical across the
    XLA oracle, the streaming kernel, and the gather A/B — and (fp
    engines) equals per-request ``generate()``.  This is the
    engine-level face of the kernel allclose test: reassociated float
    sums at 13+ blocks still never flip a greedy pick on a real
    checkpoint's logit margins."""
    p = _long_prompt(100)                       # 13 blocks of 8
    streams = {}
    for impl in ("xla", "ragged", "ragged_gather"):
        eng = _engine(long_gpt, num_slots=2, max_seq_len=128,
                      attn_impl=impl, **cfg)
        r = eng.submit(p, max_new_tokens=8)
        eng.run_until_idle()
        streams[impl] = r.result(timeout=5).tolist()
        assert eng.debug_requests()["engine"]["max_context_len"] \
            == len(p) + 8
    assert streams["xla"] == streams["ragged"] \
        == streams["ragged_gather"]
    if cfg.get("kv_dtype") is None:
        assert streams["ragged"] == _ref(long_gpt, p, 8).tolist()


@pytest.mark.pallas
@pytest.mark.longctx
def test_longctx_preempt_resume(long_gpt):
    """Preemption-resume of a LONG stream under the streaming kernel:
    a high-priority arrival evicts a 100-token-context stream
    mid-decode; the resumed continuation is token-identical to the
    uninterrupted ``generate()``."""
    eng = _engine(long_gpt, num_slots=1, max_seq_len=128,
                  attn_impl="ragged", prefill_chunk=8, async_depth=2)
    p_long = _long_prompt(100)
    p_high = _long_prompt(9, seed=5)
    low = eng.submit(p_long, max_new_tokens=10, priority=0)
    for _ in range(400):
        if len(low.generated) >= 2:
            break
        eng.step()
    assert not low.done()
    high = eng.submit(p_high, max_new_tokens=4, priority=5)
    eng.run_until_idle()
    np.testing.assert_array_equal(high.result(timeout=5),
                                  _ref(long_gpt, p_high, 4))
    np.testing.assert_array_equal(low.result(timeout=5),
                                  _ref(long_gpt, p_long, 10))
    assert low.preemptions >= 1
    if eng.prefix_cache is not None:
        eng.prefix_cache.clear()
    assert eng.block_pool.in_use() == 0


@pytest.mark.pallas
@pytest.mark.longctx
@pytest.mark.migration
def test_longctx_migration(long_gpt):
    """KV block migration of a LONG stream between streaming-kernel
    engines: export after a few emitted tokens moves the full
    13-block context, the destination finishes the stream
    token-identical to the unmigrated oracle."""
    p = _long_prompt(100)
    oracle = _engine(long_gpt, num_slots=2, max_seq_len=128,
                     attn_impl="ragged")
    r0 = oracle.submit(p, max_new_tokens=10)
    oracle.run_until_idle()
    ref = r0.result(timeout=5).tolist()

    src = _engine(long_gpt, num_slots=2, max_seq_len=128,
                  attn_impl="ragged")
    dst = _engine(long_gpt, num_slots=2, max_seq_len=128,
                  attn_impl="ragged")
    r = src.submit(p, max_new_tokens=10)
    for _ in range(400):
        if len(r.generated) >= 3 or r.done():
            break
        src.step()
    assert not r.done()
    d = src.migrate_out(request_id=r.id, min_tokens=3,
                        deliver="return", wait=False)
    verdict = None
    for _ in range(100):
        src.step()
        try:
            verdict = d.wait(0)
            break
        except TimeoutError:
            continue
    assert verdict is not None and verdict["payload"] is not None
    # a 100-token context + emitted tail crosses many blocks
    assert verdict["payload"]["kv"]["n_blocks"] >= 12
    got = None
    dm = dst.migrate_in(verdict["payload"], wait=False)
    for _ in range(100):
        dst.step()
        try:
            got = dm.wait(0)
            break
        except TimeoutError:
            continue
    assert got is not None
    dst.run_until_idle()
    assert got["request"].result(timeout=5).tolist() == ref
    src.run_until_idle()
    if src.prefix_cache is not None:
        src.prefix_cache.clear()
    assert src.block_pool.in_use() == 0


@pytest.mark.pallas
@pytest.mark.longctx
@pytest.mark.slow
def test_longctx_multithousand_token_leg(tiny_gpt):
    """The slow multi-thousand-token leg: a 2048-token prompt over a
    2304-position model, chunked prefill, streaming kernel — greedy
    decode matches per-request ``generate()`` and the walk gauge
    reads the live horizon (~256+ blocks), not the table size."""
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0, max_position=2304)
    m.eval()
    p = _long_prompt(2048, seed=11)
    eng = Engine(m, num_slots=1, max_seq_len=2304, kv_block_size=16,
                 registry=monitor.StatRegistry(), attn_impl="ragged",
                 prefill_chunk=32, async_depth=2)
    r = eng.submit(p, max_new_tokens=6)
    eng.run_until_idle()
    got = r.result(timeout=30).tolist()
    assert got == _ref(m, p, 6).tolist()
    walked = eng.registry.get(
        "serving.kv_blocks_walked_per_tick").value
    assert walked >= 2048 // 16


def test_ragged_step_failure_recovers(tiny_gpt):
    """Step-failure recovery under the ragged path: waiters unblock
    loudly, refcounts rebuild to zero, and the engine serves correct
    streams afterwards."""
    eng = _engine(tiny_gpt, num_slots=2, attn_impl="ragged")
    prompts = _prompts(2)
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    eng.step()
    eng.step()

    def boom(*a, **kw):
        raise RuntimeError("synthetic ragged dispatch failure")

    eng._ragged_fn = boom
    with pytest.raises(RuntimeError):
        eng.step()
    for r in reqs:
        with pytest.raises(RuntimeError, match="engine step failed"):
            r.result(timeout=2)
    assert eng.scheduler.occupancy() == 0
    assert eng.block_pool.in_use() == 0
    assert all(eng.block_pool.refcount(b) == 0
               for b in range(eng.block_pool.num_blocks))
    eng._ragged_fn = None
    r2 = eng.submit(prompts[0], max_new_tokens=6)
    eng.run_until_idle()
    assert r2.result(timeout=2).tolist() == \
        _ref(tiny_gpt, prompts[0], 6).tolist()
