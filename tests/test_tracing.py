"""Span tracer + chrome-trace exporter (monitor/tracing.py) and the
timeline tools: Catapult JSON validity (round-trips through ``json``,
monotonic ``ts``, well-formed ``ph`` fields), ring-buffer bounding
under sustained load, thread safety of concurrent spans against
concurrent snapshots, the RecordEvent decorator/context-manager API,
and the tools/trace_view.py + tools/timeline.py CLIs.  Pure stdlib —
no jax, no model; engine integration lives in tests/test_serving.py."""
import importlib.util
import json
import os
import threading

import pytest

from paddle_tpu.monitor.tracing import (
    NullTracer, RecordEvent, Tracer, to_chrome_trace)

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(TOOLS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_span_and_instant_events_valid_catapult():
    """Spans/instants render as Catapult JSON that json round-trips,
    with monotonic ts, matched ph fields (X carries dur, i carries
    scope), and args preserved."""
    tr = Tracer(capacity=128)
    with tr.span("tick", cat="tick", tick=1) as sp:
        tr.instant("req.queued", cat="request", req=7)
        with tr.span("decode.dispatch", batch=3):
            pass
        sp.args["emitted"] = 3
    trace = tr.chrome_trace(process_name="test")
    text = json.dumps(trace)
    back = json.loads(text)
    evs = back["traceEvents"]
    phs = {e["ph"] for e in evs}
    assert phs <= {"X", "i", "M"}
    xs = [e for e in evs if e["ph"] == "X"]
    assert all("dur" in e and e["dur"] >= 0 for e in xs)
    assert all(e["s"] == "t" for e in evs if e["ph"] == "i")
    ts = [e["ts"] for e in evs if e["ph"] != "M"]
    assert ts == sorted(ts)
    tick = next(e for e in xs if e["name"] == "tick")
    assert tick["args"] == {"tick": 1, "emitted": 3}
    # nesting: the dispatch span lies inside the tick span
    disp = next(e for e in xs if e["name"] == "decode.dispatch")
    assert tick["ts"] <= disp["ts"]
    assert disp["ts"] + disp["dur"] <= tick["ts"] + tick["dur"] + 1e-6
    inst = next(e for e in evs if e["ph"] == "i")
    assert inst["name"] == "req.queued" and inst["args"]["req"] == 7


def test_ring_buffer_bounded_under_sustained_load():
    """The per-thread ring holds at most ``capacity`` events: sustained
    load drops the OLDEST — the flight-recorder property."""
    tr = Tracer(capacity=64)
    for i in range(1000):
        with tr.span("s", i=i):
            pass
    evs = tr.events()
    assert len(evs) == 64
    # the retained window is the most recent one
    assert [e.args["i"] for e in evs] == list(range(936, 1000))
    with pytest.raises(ValueError):
        Tracer(capacity=0)


def test_thread_safety_concurrent_spans_and_snapshots():
    """4 writer threads spin spans while the main thread snapshots and
    exports continuously: no exception, every thread's ring visible,
    events bounded per thread."""
    tr = Tracer(capacity=256)
    stop = threading.Event()
    errors = []

    def spin(k):
        try:
            while not stop.is_set():
                with tr.span(f"w{k}"):
                    tr.instant(f"i{k}")
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=spin, args=(k,))
               for k in range(4)]
    for t in threads:
        t.start()
    try:
        for _ in range(100):
            evs = tr.events()
            json.dumps(tr.chrome_trace())
            assert all(evs[i].ts <= evs[i + 1].ts
                       for i in range(len(evs) - 1))
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors
    tids = {e.tid for e in tr.events()}
    assert len(tids) == 4
    per_thread = {}
    for e in tr.events():
        per_thread[e.tid] = per_thread.get(e.tid, 0) + 1
    assert all(n <= 256 for n in per_thread.values())
    names = tr.thread_names()
    assert set(names) == tids


def test_record_event_decorator_and_disable():
    """RecordEvent doubles as a decorator; a disabled tracer collects
    nothing and its span() short-circuits to the shared no-op."""
    tr = Tracer(capacity=32)

    @RecordEvent("work", tr, cat="host", n=1)
    def work(x):
        return x * 2

    assert work(21) == 42
    assert work(2) == 4
    evs = tr.events()
    assert [e.name for e in evs] == ["work", "work"]
    assert evs[0].args == {"n": 1}
    tr.enabled = False
    sp = tr.span("muted")
    with sp:
        pass
    tr.instant("muted.i")
    assert len(tr.events()) == 2  # nothing new landed
    tr.enabled = True
    with tr.span("back"):
        pass
    assert [e.name for e in tr.events()][-1] == "back"
    tr.clear()
    assert tr.events() == []


def test_null_tracer_and_emit():
    """NullTracer supports the full surface as no-ops; Tracer.emit
    back-dates an externally timed event (the compile hook's path)."""
    nt = NullTracer()
    with nt.span("x") as sp:
        sp.args["k"] = 1
    nt.instant("y")
    nt.emit("z", 0.0, 1.0)
    assert nt.events() == []
    assert nt.chrome_trace()["traceEvents"] == []
    tr = Tracer()
    tr.emit("compile:decode", 10.0, 2.5, cat="compile",
            args={"wall_ms": 2500})
    (ev,) = tr.events()
    assert ev.ts == 10.0 * 1e6 and ev.dur == 2.5 * 1e6
    assert ev.cat == "compile"


def test_to_chrome_trace_bare_event_list():
    """Without thread/process names the export has exactly one JSON
    object per event (the profiler compat contract)."""
    tr = Tracer()
    with tr.span("a"):
        pass
    trace = to_chrome_trace(tr.events())
    assert len(trace["traceEvents"]) == 1
    assert trace["traceEvents"][0]["name"] == "a"
    assert trace["displayTimeUnit"] == "ms"


def test_trace_view_summary_percentiles(tmp_path):
    """tools/trace_view.py aggregates complete-events per name with
    count/total/p50/p99 (interpolated), category filter included."""
    tv = _load_tool("trace_view")
    events = ([{"name": "tick", "ph": "X", "ts": i * 100.0,
                "dur": (i + 1) * 1000.0, "cat": "tick"}
               for i in range(100)] +
              [{"name": "admit", "ph": "X", "ts": 0.0, "dur": 500.0,
                "cat": "serving"},
               {"name": "req.queued", "ph": "i", "ts": 0.0,
                "cat": "request"}])
    rows = tv.summarize(events)
    assert [r["name"] for r in rows] == ["tick", "admit"]  # by total
    tick = rows[0]
    assert tick["count"] == 100
    # durs are 1..100 ms; numpy-linear percentiles over them
    assert tick["p50_ms"] == pytest.approx(50.5)
    assert tick["p99_ms"] == pytest.approx(99.01)
    assert rows[1]["count"] == 1 and rows[1]["p50_ms"] == 0.5
    assert tv.summarize(events, cat="tick")[0]["name"] == "tick"
    assert len(tv.summarize(events, cat="tick")) == 1
    # CLI end to end over a file
    path = tmp_path / "t.json"
    path.write_text(json.dumps({"traceEvents": events}))
    assert tv.main([str(path)]) == 0
    assert tv.main([str(path), "--cat", "nope"]) == 1
    table = tv.format_table(rows)
    assert "tick" in table and "p99(ms)" in table


def test_timeline_merge_assigns_pids(tmp_path):
    """tools/timeline.py merges N traces into one timeline with
    distinct pids, preserves flight-recorder metadata, and accepts
    both object-form and bare-list files."""
    tl = _load_tool("timeline")
    t1 = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 999, "tid": 0,
         "args": {"name": "engine"}},
        {"name": "tick", "ph": "X", "ts": 0.0, "dur": 5.0,
         "pid": 999, "tid": 1, "cat": "tick"}],
        "metadata": {"flight-recorder": {"error": "boom"}}}
    t2 = [{"name": "step", "ph": "X", "ts": 1.0, "dur": 2.0,
           "pid": 999, "tid": 1, "cat": "host"}]
    p1, p2 = tmp_path / "a.json", tmp_path / "b.json"
    p1.write_text(json.dumps(t1))
    p2.write_text(json.dumps(t2))
    out = tmp_path / "merged.json"
    assert tl.main([str(p1), str(p2), "--out", str(out)]) == 0
    merged = json.loads(out.read_text())
    pids = {e["pid"] for e in merged["traceEvents"]}
    assert pids == {0, 1}
    assert merged["metadata"]["flight-recorder"]["error"] == "boom"
    # the bare-list source got a synthesized process_name row
    metas = [e for e in merged["traceEvents"]
             if e["ph"] == "M" and e["pid"] == 1]
    assert metas and metas[0]["args"]["name"].endswith("b.json")


def test_timeline_rejects_non_trace(tmp_path):
    tl = _load_tool("timeline")
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"nope": 1}))
    with pytest.raises(ValueError, match="traceEvents"):
        tl.load_trace(str(bad))


def test_dead_thread_lanes_pruned_and_idents_not_recycled():
    """Lanes are per thread LIFETIME: a new thread never inherits a
    dead thread's lane/name (even if the OS recycles the ident), dead
    lanes are retained for post-mortems until max_threads, then pruned
    oldest-first — live lanes never evicted."""
    tr = Tracer(capacity=16, max_threads=4)
    with tr.span("main.keepalive"):
        pass  # the live main-thread lane that must survive pruning

    def one_span(k):
        t = threading.Thread(target=lambda: tr.instant(f"w{k}"),
                             name=f"worker-{k}")
        t.start()
        t.join()

    for k in range(10):
        one_span(k)
    names = tr.thread_names()
    assert len(names) <= 4                      # bounded
    assert "MainThread" in names.values()       # live lane retained
    # every lane id is unique per thread lifetime: 11 threads emitted,
    # so the newest lane id outgrew the bound — no reuse happened
    assert max(names) > 4
    # the retained worker lanes are the most recent ones
    worker_names = sorted(v for v in names.values()
                          if v.startswith("worker-"))
    assert worker_names == [f"worker-{k}" for k in (7, 8, 9)]
    # and the main lane still collects
    with tr.span("main.again"):
        pass
    assert any(e.name == "main.again" for e in tr.events())


def test_trace_view_wall_summary(tmp_path, capsys):
    """--wall reports per-tick wall time vs summed phase time: with
    the async engine loop, host.overlap spans run concurrently with
    device compute, so phase totals legitimately exceed wall — the
    summary surfaces the divergence the plain table double-counts."""
    tv = _load_tool("trace_view")
    # 2 ticks of 10 ms wall; phases sum to 14 ms per tick because
    # 5 ms of host.overlap + 2 ms of d2h wait ran concurrently
    events = []
    for i in range(2):
        t0 = i * 20000.0
        events += [
            {"name": "tick", "ph": "X", "ts": t0, "dur": 10000.0,
             "cat": "tick"},
            {"name": "decode.dispatch", "ph": "X", "ts": t0,
             "dur": 7000.0, "cat": "serving"},
            {"name": "host.overlap", "ph": "X", "ts": t0 + 1000.0,
             "dur": 5000.0, "cat": "serving"},
            {"name": "decode.d2h_wait", "ph": "X", "ts": t0 + 7000.0,
             "dur": 2000.0, "cat": "serving"},
        ]
    w = tv.wall_summary(events)
    assert w["ticks"] == 2
    assert w["wall_ms"] == pytest.approx(20.0)
    assert w["phase_ms"] == pytest.approx(28.0)
    assert w["per_tick_wall_ms"] == pytest.approx(10.0)
    assert w["per_tick_phase_ms"] == pytest.approx(14.0)
    assert w["overlap_ms"] == pytest.approx(10.0)
    assert w["d2h_wait_ms"] == pytest.approx(4.0)
    # CLI: --wall appends the summary after the table
    path = tmp_path / "t.json"
    path.write_text(json.dumps({"traceEvents": events}))
    assert tv.main([str(path), "--wall"]) == 0
    out = capsys.readouterr().out
    assert "wall 20.000 ms" in out
    assert "host.overlap 10.000 ms" in out
    assert "concurrently" in out
    # no ragged dispatches in this trace: the kernel line stays out
    assert "decode.ragged" not in out


def test_trace_view_surfaces_ragged_kernel_dispatches(tmp_path,
                                                      capsys):
    """--wall breaks out ``decode.ragged`` spans (the GATHER-body
    Pallas dispatches of ``Engine(attn_impl="ragged_gather")``) so
    a trace shows at a glance whether the kernel or the per-shape XLA
    programs (``decode.dispatch``) served the tick."""
    tv = _load_tool("trace_view")
    events = [
        {"name": "tick", "ph": "X", "ts": 0.0, "dur": 10000.0,
         "cat": "tick"},
        {"name": "decode.ragged", "ph": "X", "ts": 500.0,
         "dur": 6000.0, "cat": "serving",
         "args": {"chunks": 1, "w": 8}},
        {"name": "tick", "ph": "X", "ts": 20000.0, "dur": 10000.0,
         "cat": "tick"},
        {"name": "decode.ragged", "ph": "X", "ts": 20500.0,
         "dur": 5000.0, "cat": "serving"},
    ]
    w = tv.wall_summary(events)
    assert w["ragged_dispatches"] == 2
    assert w["ragged_ms"] == pytest.approx(11.0)
    assert w["ragged_stream_dispatches"] == 0
    path = tmp_path / "ragged.json"
    path.write_text(json.dumps({"traceEvents": events}))
    assert tv.main([str(path), "--wall"]) == 0
    out = capsys.readouterr().out
    assert "decode.ragged 11.000 ms over 2 Pallas" in out
    assert "decode.ragged_stream" not in out


def test_trace_view_surfaces_ragged_stream_dispatches(tmp_path,
                                                      capsys):
    """--wall breaks out ``decode.ragged_stream`` spans (the
    streaming online-softmax dispatches of
    ``Engine(attn_impl="ragged")``) SEPARATELY from the gather body's
    ``decode.ragged``, and sums the spans' ``kv_blocks_walked`` arg —
    per-tick block-walk cost, attributable from a trace alone."""
    tv = _load_tool("trace_view")
    events = [
        {"name": "tick", "ph": "X", "ts": 0.0, "dur": 10000.0,
         "cat": "tick"},
        {"name": "decode.ragged_stream", "ph": "X", "ts": 500.0,
         "dur": 6000.0, "cat": "serving",
         "args": {"chunks": 1, "w": 8, "kv_blocks_walked": 12}},
        {"name": "tick", "ph": "X", "ts": 20000.0, "dur": 10000.0,
         "cat": "tick"},
        {"name": "decode.ragged_stream", "ph": "X", "ts": 20500.0,
         "dur": 5000.0, "cat": "serving",
         "args": {"kv_blocks_walked": 14}},
        {"name": "decode.ragged", "ph": "X", "ts": 26000.0,
         "dur": 2000.0, "cat": "serving"},
    ]
    w = tv.wall_summary(events)
    assert w["ragged_stream_dispatches"] == 2
    assert w["ragged_stream_ms"] == pytest.approx(11.0)
    assert w["kv_blocks_walked"] == 26
    assert w["ragged_dispatches"] == 1      # the gather A/B line
    path = tmp_path / "ragged_stream.json"
    path.write_text(json.dumps({"traceEvents": events}))
    assert tv.main([str(path), "--wall"]) == 0
    out = capsys.readouterr().out
    assert "decode.ragged_stream 11.000 ms over 2 streaming" in out
    assert "kv blocks walked 26 (13.0/tick)" in out
    assert "decode.ragged 2.000 ms over 1 Pallas" in out


def test_trace_view_surfaces_offload_transfers(tmp_path, capsys):
    """--wall breaks out ``offload.demote`` / ``offload.promote``
    spans (the host-RAM KV tier of ``Engine(kv_host_mb=...)``) so a
    trace shows at a glance what the second tier's d2h spills and h2d
    restores cost next to decode itself."""
    tv = _load_tool("trace_view")
    events = [
        {"name": "tick", "ph": "X", "ts": 0.0, "dur": 10000.0,
         "cat": "tick"},
        {"name": "offload.demote", "ph": "X", "ts": 500.0,
         "dur": 1500.0, "cat": "serving",
         "args": {"key": "ab12", "stored": True}},
        {"name": "offload.demote", "ph": "X", "ts": 2500.0,
         "dur": 500.0, "cat": "serving"},
        {"name": "tick", "ph": "X", "ts": 20000.0, "dur": 10000.0,
         "cat": "tick"},
        {"name": "offload.promote", "ph": "X", "ts": 20500.0,
         "dur": 3000.0, "cat": "serving", "args": {"blocks": 3}},
    ]
    w = tv.wall_summary(events)
    assert w["offload_demotes"] == 2
    assert w["offload_demote_ms"] == pytest.approx(2.0)
    assert w["offload_promotes"] == 1
    assert w["offload_promote_ms"] == pytest.approx(3.0)
    path = tmp_path / "offload.json"
    path.write_text(json.dumps({"traceEvents": events}))
    assert tv.main([str(path), "--wall"]) == 0
    out = capsys.readouterr().out
    assert "offload.demote 2.000 ms over 2 block demote(s)" in out
    assert "offload.promote 3.000 ms over 1 restore(s)" in out
    assert "host-RAM KV tier" in out
    # a trace with no offload traffic keeps the line out entirely
    quiet = [e for e in events if not e["name"].startswith("offload.")]
    assert not (tv.wall_summary(quiet)["offload_demotes"]
                or tv.wall_summary(quiet)["offload_promotes"])
    path.write_text(json.dumps({"traceEvents": quiet}))
    assert tv.main([str(path), "--wall"]) == 0
    assert "offload." not in capsys.readouterr().out


def test_trace_view_lifecycle_instants(tmp_path, capsys):
    """tools/trace_view.py --lifecycle counts instant events by name
    with a [reason] breakdown — the req.preempted / req.resumed /
    req.shed overload lifecycle renders alongside the span table."""
    tv = _load_tool("trace_view")
    events = [
        {"name": "tick", "ph": "X", "ts": 0.0, "dur": 100.0,
         "cat": "tick"},
        {"name": "req.queued", "ph": "i", "ts": 1.0, "cat": "request",
         "args": {"req": 1}},
        {"name": "req.preempted", "ph": "i", "ts": 2.0,
         "cat": "request", "args": {"req": 1, "slot": 0}},
        {"name": "req.resumed", "ph": "i", "ts": 3.0,
         "cat": "request", "args": {"req": 1}},
        {"name": "req.shed", "ph": "i", "ts": 4.0, "cat": "request",
         "args": {"req": 2, "reason": "deadline"}},
        {"name": "req.shed", "ph": "i", "ts": 5.0, "cat": "request",
         "args": {"req": 3, "reason": "queue_full"}},
        {"name": "req.shed", "ph": "i", "ts": 6.0, "cat": "request",
         "args": {"req": 4, "reason": "deadline"}},
        {"name": "fault.injected", "ph": "i", "ts": 7.0,
         "cat": "fault", "args": {"site": "dispatch"}},
    ]
    rows = dict(tv.lifecycle_summary(events))
    assert rows["req.preempted"] == 1
    assert rows["req.resumed"] == 1
    assert rows["req.shed[deadline]"] == 2
    assert rows["req.shed[queue_full]"] == 1
    assert rows["fault.injected"] == 1
    assert "tick" not in rows            # complete-events excluded
    path = tmp_path / "t.json"
    path.write_text(json.dumps({"traceEvents": events}))
    assert tv.main([str(path), "--lifecycle"]) == 0
    out = capsys.readouterr().out
    assert "req.preempted" in out and "req.shed[deadline]" in out


def test_timeline_lifecycle_counts(tmp_path, capsys):
    """tools/timeline.py --lifecycle prints per-source instant counts
    (stderr) while the merged trace stays intact on stdout."""
    tl = _load_tool("timeline")
    t1 = {"traceEvents": [
        {"name": "req.preempted", "ph": "i", "ts": 1.0,
         "cat": "request", "args": {"req": 9}},
        {"name": "req.shed", "ph": "i", "ts": 2.0, "cat": "request",
         "args": {"req": 10, "reason": "rate_limited"}},
        {"name": "tick", "ph": "X", "ts": 0.0, "dur": 3.0,
         "cat": "tick"}]}
    assert tl.lifecycle_counts(t1) == {"req.preempted": 1,
                                       "req.shed[rate_limited]": 1}
    p1 = tmp_path / "a.json"
    p1.write_text(json.dumps(t1))
    out_path = tmp_path / "m.json"
    assert tl.main([str(p1), "--lifecycle",
                    "--out", str(out_path)]) == 0
    err = capsys.readouterr().err
    assert "req.preempted=1" in err
    merged = json.loads(out_path.read_text())
    assert len(merged["traceEvents"]) == 4  # 3 events + process_name
