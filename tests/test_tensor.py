import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import Tensor


def test_to_tensor_roundtrip():
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    t = paddle_tpu.to_tensor(x)
    assert t.shape == [3, 4]
    assert t.dtype == "float32"
    np.testing.assert_array_equal(t.numpy(), x)


def test_default_dtype_f64_literal():
    t = paddle_tpu.to_tensor([1.0, 2.0])
    assert t.dtype == "float32"


def test_int_dtype_preserved():
    t = paddle_tpu.to_tensor(np.array([1, 2, 3]))
    assert t.dtype in ("int64", "int32")


def test_arithmetic_operators():
    a = paddle_tpu.to_tensor([1.0, 2.0, 3.0])
    b = paddle_tpu.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9], rtol=1e-5)
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])
    np.testing.assert_allclose((2 + a).numpy(), [3, 4, 5])
    np.testing.assert_allclose((10 - a).numpy(), [9, 8, 7])


def test_comparisons():
    a = paddle_tpu.to_tensor([1.0, 2.0, 3.0])
    b = paddle_tpu.to_tensor([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])


def test_indexing():
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    t = paddle_tpu.to_tensor(x)
    np.testing.assert_array_equal(t[1].numpy(), x[1])
    np.testing.assert_array_equal(t[1:3, 2:4].numpy(), x[1:3, 2:4])
    idx = paddle_tpu.to_tensor(np.array([0, 2]))
    np.testing.assert_array_equal(t[idx].numpy(), x[[0, 2]])


def test_setitem():
    t = paddle_tpu.zeros([3, 3])
    t[1, 1] = 5.0
    assert t.numpy()[1, 1] == 5.0


def test_item_and_scalar():
    t = paddle_tpu.to_tensor(3.5)
    assert t.item() == pytest.approx(3.5)
    assert float(t) == pytest.approx(3.5)


def test_astype_cast():
    t = paddle_tpu.to_tensor([1.5, 2.5])
    ti = t.astype("int32")
    assert ti.dtype == "int32"


def test_set_value_and_fill():
    t = paddle_tpu.ones([2, 2])
    t.set_value(np.full((2, 2), 7.0, np.float32))
    assert t.numpy()[0, 0] == 7.0
    t.zero_()
    assert t.numpy().sum() == 0.0


def test_clone_detach():
    t = paddle_tpu.to_tensor([1.0], stop_gradient=False)
    c = t.detach()
    assert c.stop_gradient
    cl = t.clone()
    np.testing.assert_array_equal(cl.numpy(), t.numpy())


def test_creation_ops():
    assert paddle_tpu.zeros([2, 3]).shape == [2, 3]
    assert paddle_tpu.ones([2]).numpy().sum() == 2.0
    assert paddle_tpu.full([2, 2], 3.0).numpy()[0, 0] == 3.0
    ar = paddle_tpu.arange(0, 10, 2)
    np.testing.assert_array_equal(ar.numpy(), [0, 2, 4, 6, 8])
    ey = paddle_tpu.eye(3)
    np.testing.assert_array_equal(ey.numpy(), np.eye(3, dtype=np.float32))
    ls = paddle_tpu.linspace(0, 1, 5)
    np.testing.assert_allclose(ls.numpy(), np.linspace(0, 1, 5),
                               rtol=1e-6)


def test_random_reproducible():
    paddle_tpu.seed(7)
    a = paddle_tpu.rand([4]).numpy()
    paddle_tpu.seed(7)
    b = paddle_tpu.rand([4]).numpy()
    np.testing.assert_array_equal(a, b)


def test_randperm_and_randint():
    p = paddle_tpu.randperm(10)
    assert sorted(p.tolist()) == list(range(10))
    r = paddle_tpu.randint(0, 5, [100])
    assert r.numpy().min() >= 0 and r.numpy().max() < 5
