"""Round-2 compat surfaces: fluid.optimizer 1.x classes + EMA/ModelAverage/
Lookahead, fluid.dygraph submodules & 1.x layers, fleet Fleet/UtilBase/
data generators/metrics, utils helpers, paddle.framework re-exports,
vision/text dataset families."""
import io
import contextlib
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu import nn

T = paddle.to_tensor


def _problem():
    rng = np.random.RandomState(0)
    x = rng.rand(16, 4).astype("float32")
    y = rng.rand(16, 1).astype("float32")
    return T(x), T(y)


class TestFluidOptimizers:
    @pytest.mark.parametrize("name", [
        "SGDOptimizer", "MomentumOptimizer", "AdagradOptimizer",
        "AdamOptimizer", "AdamaxOptimizer", "RMSPropOptimizer",
        "LambOptimizer", "DecayedAdagradOptimizer", "FtrlOptimizer",
    ])
    def test_1x_optimizers_train(self, name):
        x, y = _problem()
        paddle.seed(0)
        net = nn.Linear(4, 1)
        cls = getattr(fluid.optimizer, name)
        kwargs = dict(learning_rate=0.05,
                      parameter_list=net.parameters())
        if name == "MomentumOptimizer":
            kwargs["momentum"] = 0.9
        opt = cls(**kwargs)
        first = last = None
        for _ in range(12):
            loss = paddle.mean((net(x) - y) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            v = float(loss.numpy())
            first = first if first is not None else v
            last = v
        assert last < first, (name, first, last)

    def test_ema_apply_restore(self):
        x, y = _problem()
        paddle.seed(1)
        net = nn.Linear(4, 1)
        opt = fluid.optimizer.SGDOptimizer(
            learning_rate=0.1, parameter_list=net.parameters())
        ema = fluid.optimizer.ExponentialMovingAverage(0.5)
        for _ in range(5):
            loss = paddle.mean((net(x) - y) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            ema.update(net)
        raw = net(x).numpy()
        with ema.apply():
            inside = net(x).numpy()
            # EMA weights must stay the same order of magnitude as the raw
            # weights (the round-1 bug scaled them ~1/(1-0.999^N))
            for i, p in ema._params.items():
                w = np.asarray(p._data)
                b = np.asarray(ema._backup[i])
                assert np.abs(w).max() <= 10 * max(np.abs(b).max(), 1e-6), (
                    "EMA apply() produced runaway-scaled weights")
        after = net(x).numpy()
        assert not np.allclose(raw, inside)
        np.testing.assert_allclose(raw, after)  # restored

    def test_ema_matches_hand_computation(self):
        paddle.seed(3)
        net = nn.Linear(3, 2)
        decay = 0.9
        ema = fluid.optimizer.ExponentialMovingAverage(decay)
        hand = None
        steps = 4
        for t in range(steps):
            # mutate params deterministically, then update the EMA
            for p in net.parameters():
                p._data = p._data + 0.1
            ema.update(net)
            vals = [np.asarray(p._data) for p in net.parameters()]
            if hand is None:
                hand = [np.zeros_like(v) for v in vals]
            hand = [decay * h + (1 - decay) * v for h, v in zip(hand, vals)]
        bias = 1.0 - decay ** steps
        with ema.apply():
            for p, h in zip(net.parameters(), hand):
                np.testing.assert_allclose(
                    np.asarray(p._data), h / bias, rtol=1e-5)

    def test_model_average(self):
        x, y = _problem()
        paddle.seed(2)
        net = nn.Linear(4, 1)
        ma = fluid.optimizer.ModelAverage(
            0.15, min_average_window=2, max_average_window=4,
            parameters=net.parameters())
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        for _ in range(6):
            loss = paddle.mean((net(x) - y) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            ma.update()
        raw = net(x).numpy()
        with ma.apply():
            avg = net(x).numpy()
        assert not np.allclose(raw, avg)

    def test_lookahead_converges(self):
        x, y = _problem()
        paddle.seed(3)
        net = nn.Linear(4, 1)
        look = fluid.optimizer.LookaheadOptimizer(
            paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=net.parameters()),
            alpha=0.5, k=3)
        first = last = None
        for _ in range(15):
            loss = paddle.mean((net(x) - y) ** 2)
            loss.backward()
            look.step()
            look.clear_grad()
            v = float(loss.numpy())
            first = first if first is not None else v
            last = v
        assert last < first

    def test_recompute_pipeline_wrappers(self):
        inner = paddle.optimizer.SGD(learning_rate=0.1)
        rec = fluid.optimizer.RecomputeOptimizer(inner)
        rec._set_checkpoints([])
        assert rec.get_lr() == pytest.approx(0.1)
        pipe = fluid.optimizer.PipelineOptimizer(inner, num_microbatches=4)
        assert pipe.num_microbatches == 4


class TestDygraphCompat:
    def test_lr_scheduler_names(self):
        dg = fluid.dygraph
        s = dg.CosineDecay(0.1, T_max=10)
        assert callable(s)
        r = dg.ReduceLROnPlateau(learning_rate=0.1)
        assert hasattr(r, "step")
        w = dg.LinearLrWarmup(0.1, warmup_steps=5, start_lr=0.0,
                              end_lr=0.1)
        assert callable(w)

    def test_layer_aliases_forward(self):
        dg = fluid.dygraph
        x = T(np.random.RandomState(0).randn(2, 3, 8, 8).astype("float32"))
        layer = dg.nn.InstanceNorm(3)
        assert layer(x).shape == [2, 3, 8, 8]
        pr = dg.nn.PRelu(num_parameters=1)
        assert pr(x).shape == [2, 3, 8, 8]

    def test_save_load_dygraph(self, tmp_path):
        net = nn.Linear(3, 2)
        p = str(tmp_path / "m")
        fluid.dygraph.save_dygraph(net.state_dict(), p)
        params, opt = fluid.dygraph.load_dygraph(p)
        assert opt is None
        assert set(params) == set(net.state_dict())

    def test_no_grad(self):
        x = T(np.ones(2, "float32"))
        x.stop_gradient = False
        with fluid.dygraph.no_grad():
            y = x * 2
        assert y.stop_gradient

    def test_set_global_initializer(self):
        from paddle_tpu.nn import initializer as I
        I.set_global_initializer(I.Constant(0.5), I.Constant(-0.5))
        try:
            lin = nn.Linear(3, 2)
            np.testing.assert_allclose(lin.weight.numpy(), 0.5)
            np.testing.assert_allclose(lin.bias.numpy(), -0.5)
        finally:
            I.set_global_initializer(None, None)

    def test_xavier_msra_facades(self):
        from paddle_tpu.nn import initializer as I
        w = I.Xavier(uniform=True)([64, 64])
        assert np.asarray(w).std() > 0
        m = I.MSRA(uniform=False)([64, 64])
        assert np.asarray(m).std() > 0


class TestFleetRound2:
    def test_fleet_class_and_util(self):
        from paddle_tpu.distributed import fleet
        f = fleet.Fleet()
        assert f.worker_num() >= 1
        # reference style: fleet.util is the UtilBase instance
        assert fleet.util.get_file_shard(["a", "b", "c"]) == \
            ["a", "b", "c"]
        assert float(fleet.util.all_reduce(np.asarray([2.0]))) == 2.0
        assert f.util is fleet.util

    def test_data_generator_format(self):
        from paddle_tpu.distributed import fleet

        class G(fleet.MultiSlotDataGenerator):
            def generate_sample(self, line):
                def it():
                    yield [("words", [3, 4]), ("label", [1])]
                return it

        g = G()
        g.set_batch(1)
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            g.run_from_memory()
        assert buf.getvalue().strip() == "2 3 4 1 1"

    def test_metrics(self):
        from paddle_tpu.distributed import fleet
        assert fleet.metrics.acc(9, 10) == pytest.approx(0.9)
        pos = np.zeros(10)
        neg = np.zeros(10)
        pos[9] = 5
        neg[0] = 5
        assert fleet.metrics.auc(pos, neg) == pytest.approx(1.0)
        assert fleet.metrics.rmse(np.asarray([4.0]), 4) == pytest.approx(1)


class TestUtilsFramework:
    def test_deprecated_decorator(self):
        @paddle.utils.deprecated(update_to="paddle.new_op", since="2.0")
        def old_op():
            return 42

        with pytest.warns(DeprecationWarning):
            assert old_op() == 42

    def test_require_version(self):
        assert paddle.utils.require_version("0.0.1")
        with pytest.raises(Exception):
            paddle.utils.require_version("999.0.0")

    def test_framework_reexports(self):
        import paddle_tpu.framework as fw
        assert fw.get_default_dtype() == "float32"
        t = fw.create_parameter([2, 2], "float32")
        assert t.shape == [2, 2]
        assert fw.CPUPlace is not None and fw.LayerList is not None


class TestDatasetFamilies:
    def test_flowers_voc_synthetic(self):
        os.environ["PADDLE_TPU_SYNTH_N"] = "8"
        try:
            from paddle_tpu.vision import datasets as vds
            fl = vds.Flowers(mode="test")
            img, lab = fl[0]
            assert img.shape == (224, 224, 3)
            voc = vds.VOC2012(mode="valid")
            im, mask = voc[1]
            assert mask.shape == (224, 224)
        finally:
            os.environ.pop("PADDLE_TPU_SYNTH_N", None)

    def test_folder_datasets(self, tmp_path):
        from paddle_tpu.vision import datasets as vds
        for c in ("a", "b"):
            (tmp_path / c).mkdir()
            for i in range(2):
                np.save(str(tmp_path / c / f"{i}.npy"),
                        np.random.rand(4, 4, 3).astype("float32"))
        df = vds.DatasetFolder(str(tmp_path))
        assert len(df) == 4 and df.classes == ["a", "b"]
        x, y = df[3]
        assert x.shape == (4, 4, 3) and int(y) == 1
        imf = vds.ImageFolder(str(tmp_path))
        (sample,) = imf[0]
        assert sample.shape == (4, 4, 3)

    def test_submodule_aliases(self):
        import paddle_tpu as p
        assert p.vision.datasets.mnist.MNIST is p.vision.datasets.MNIST
        assert p.vision.models.resnet.resnet50 is p.vision.models.resnet50
        assert p.text.datasets.imdb.Imdb is not None
        tf = p.vision.transforms.functional
        out = tf.to_tensor(np.random.rand(6, 6, 3).astype("float32"))
        assert np.asarray(out).shape == (3, 6, 6)


class TestFluidMetricsIo:
    def test_chunk_evaluator_iob(self):
        from paddle_tpu.fluid.metrics import ChunkEvaluator, chunk_count
        m = ChunkEvaluator()
        # IOB, 1 type: B=0 I=1 Outside=2
        ni, nl, nc = chunk_count([0, 1, 2, 0], [0, 1, 2, 0], "IOB", 1)
        m.update(ni, nl, nc)
        assert m.eval() == (1.0, 1.0, 1.0)
        ni2, nl2, nc2 = chunk_count([0, 2, 2, 0], [0, 1, 2, 0], "IOB", 1)
        assert (ni2, nl2, nc2) == (2, 2, 1)

    def test_chunk_eval_layer(self):
        import paddle_tpu.fluid as fluid
        pre, rec, f1, ni, nl, nc = fluid.layers.chunk_eval(
            paddle.to_tensor(np.array([[0, 1, 2, 0]], np.int64)),
            paddle.to_tensor(np.array([[0, 1, 2, 0]], np.int64)),
            "IOB", 1)
        assert float(f1.numpy()[0]) == 1.0
        assert int(nc.numpy()[0]) == 2

    def test_detection_map(self):
        from paddle_tpu.fluid.metrics import DetectionMAP
        d = DetectionMAP()
        d.update([[0, 0.9, 0, 0, 10, 10]], [[0, 0, 0, 10, 10]])
        d.update([[0, 0.8, 50, 50, 60, 60]], [[0, 0, 0, 10, 10]])
        assert d.eval() == pytest.approx(0.5, abs=1e-6)
        d11 = DetectionMAP(ap_version="11point")
        d11.update([[0, 0.9, 0, 0, 10, 10]], [[0, 0, 0, 10, 10]])
        assert d11.eval() > 0.9

    def test_edit_distance_and_auc_metrics(self):
        from paddle_tpu.fluid.metrics import EditDistance, Auc
        e = EditDistance()
        e.update([0.0, 2.0])
        assert e.eval() == (1.0, 0.5)
        a = Auc()
        a.update(np.array([0.9, 0.1]), np.array([1, 0]))
        assert a.eval() == 1.0

    def test_fluid_io_params_roundtrip(self, tmp_path):
        import paddle_tpu.fluid as fluid
        from paddle_tpu import static
        paddle.enable_static()
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                x = static.data("x", [4, 3], "float32")
                out = fluid.layers.fc(x, 2)
            exe = fluid.Executor()
            exe.run(startup)
            xv = np.random.RandomState(0).rand(4, 3).astype("float32")
            (before,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
            fluid.io.save_params(exe, str(tmp_path), main_program=main)
            # perturb then restore
            for t in main.captures.values():
                t.set_value(np.zeros_like(np.asarray(t.numpy())))
            fluid.io.load_params(exe, str(tmp_path), main_program=main)
            (after,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
            np.testing.assert_allclose(np.asarray(before),
                                       np.asarray(after), rtol=1e-6)
        finally:
            paddle.disable_static()

    def test_batch_reader(self):
        import paddle_tpu.fluid as fluid

        def reader():
            yield from range(7)

        batches = list(fluid.io.batch(reader, 3)())
        assert batches == [[0, 1, 2], [3, 4, 5], [6]]
        batches2 = list(fluid.io.batch(reader, 3, drop_last=True)())
        assert batches2 == [[0, 1, 2], [3, 4, 5]]

    def test_data_feeder(self):
        import paddle_tpu.fluid as fluid
        fd = fluid.DataFeeder(feed_list=["img", "label"])
        feed = fd.feed([(np.zeros((2, 2)), 1), (np.ones((2, 2)), 0)])
        assert feed["img"].shape == (2, 2, 2)
        assert feed["label"].tolist() == [1, 0]


class TestReviewRegressions3:
    def test_set_gradient_clip_consumed_by_optimizer(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu import nn as _nn, optimizer as _opt
        clip = _nn.ClipGradByGlobalNorm(1e-8)  # effectively zeroes grads
        fluid.clip.set_gradient_clip(clip)
        try:
            net = _nn.Linear(4, 1)
            opt = _opt.SGD(learning_rate=1.0,
                           parameters=net.parameters())
            assert opt._grad_clip is clip
            w0 = net.weight.numpy().copy()
            x = T(np.ones((2, 4), "float32"))
            loss = paddle.mean(net(x))
            loss.backward()
            opt.step()
            # clipped to ~0 norm: weights barely move despite lr=1.0
            assert np.abs(net.weight.numpy() - w0).max() < 1e-6
        finally:
            fluid.clip.set_gradient_clip(None)

    def test_fluid_io_full_surface(self):
        import paddle_tpu.fluid as fluid
        for name in ("DataLoader", "Dataset", "BatchSampler",
                     "DataFeeder", "InMemoryDataset", "QueueDataset",
                     "save_params", "load_persistables", "batch"):
            assert hasattr(fluid.io, name), name
        import paddle_tpu as p
        assert fluid.DataFeeder is p.io.DataFeeder

    def test_auc_vectorized_update(self):
        from paddle_tpu.fluid.metrics import Auc
        a = Auc()
        rng = np.random.RandomState(0)
        preds = rng.rand(1000)
        labels = (preds + 0.3 * rng.randn(1000)) > 0.5
        a.update(preds, labels)
        v = a.eval()
        assert 0.8 < v <= 1.0


class TestTensorModelMethodParity:
    def test_tensor_varbase_methods(self):
        t = T(np.ones((2, 2), "float32"))
        assert t.cuda() is t and t.value() is t
        assert t.gradient() is None
        t.stop_gradient = False
        (t * 3).sum().backward()
        np.testing.assert_allclose(t.gradient(), 3.0)

    def test_model_mode(self):
        net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m = paddle.Model(net)
        assert m.mode == "train"
        m.mode = "eval"
        assert not net.training
        m.mode = "train"
        assert net.training


class TestIncubateHelpers:
    def test_layer_helper_create_parameter_and_activation(self):
        from paddle_tpu.incubate import LayerHelper
        h = LayerHelper("custom_fc", act="relu")
        w = h.create_parameter(shape=[3, 4], dtype="float32")
        assert list(w.shape) == [3, 4]
        out = h.append_activation(paddle.to_tensor(
            np.array([-1.0, 2.0], "float32")))
        np.testing.assert_allclose(out.numpy(), [0.0, 2.0])

    def test_load_op_library_guides_to_primitive(self):
        from paddle_tpu.incubate import load_op_library
        with pytest.raises(NotImplementedError, match="primitive"):
            load_op_library("/tmp/libfoo.so")


class TestReaderNamespace:
    def test_reader_reachable_from_root(self):
        assert hasattr(paddle, "reader")
        assert callable(paddle.reader.shuffle)

    def test_layer_helper_named_attr_memoizes(self):
        """A NAMED attr returns the same Parameter across calls
        (reference: block-variable reuse); unnamed stays fresh."""
        from paddle_tpu.incubate import LayerHelper
        h = LayerHelper("memo_fc")
        attr = nn.ParamAttr(name="memo_fc_w")
        p1 = h.create_parameter(attr=attr, shape=[2, 2])
        p2 = h.create_parameter(attr=attr, shape=[2, 2])
        assert p1 is p2
        q1 = h.create_parameter(shape=[2, 2])
        q2 = h.create_parameter(shape=[2, 2])
        assert q1 is not q2

    def test_layer_helper_registry_cleared_by_seed(self):
        from paddle_tpu.incubate import LayerHelper
        h = LayerHelper("seed_fc")
        attr = nn.ParamAttr(name="seed_fc_w")
        p1 = h.create_parameter(attr=attr, shape=[2, 2])
        paddle.seed(123)
        p2 = h.create_parameter(attr=attr, shape=[2, 2])
        assert p1 is not p2  # fresh seed => fresh parameters
