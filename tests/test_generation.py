"""KV-cached generation: cached incremental decode must match full
re-forward argmax at every step."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTModel


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0)
    m.eval()
    return m


def test_cached_generate_matches_full_forward(tiny_gpt):
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (2, 6)).astype(np.int32)
    out = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=4)
    assert out.shape == [2, 10]
    # replay without cache: each new token = argmax of full forward
    seq = ids.copy()
    for _ in range(4):
        logits = tiny_gpt(paddle.to_tensor(seq))
        nxt = logits.numpy()[:, -1, :].argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(out.numpy(), seq)


@pytest.mark.slow
def test_generate_topk_sampling_reproducible(tiny_gpt):
    ids = np.zeros((1, 3), np.int32)
    a = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=5,
                          top_k=5, seed=42)
    b = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=5,
                          top_k=5, seed=42)
    np.testing.assert_array_equal(a.numpy(), b.numpy())
    assert a.shape == [1, 8]


def test_generate_eos_stops(tiny_gpt):
    ids = np.zeros((1, 3), np.int32)
    full = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=8)
    first_tok = int(full.numpy()[0, 3])
    out = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=8,
                            eos_token_id=first_tok)
    assert out.shape[1] == 4  # stopped right after the eos token


# ---- regressions from code review ----------------------------------------

def test_generate_rejects_position_overflow(tiny_gpt):
    max_pos = tiny_gpt.embeddings.position_embeddings.weight.shape[0]
    ids = np.zeros((1, max_pos - 2), np.int32)
    with pytest.raises(ValueError):
        tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=8)


def test_generate_temperature_alone_samples(tiny_gpt):
    ids = np.zeros((1, 3), np.int32)
    greedy = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6)
    hot = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6,
                            temperature=5.0, seed=1)
    # high temperature with no top_k must actually sample (not argmax)
    assert not np.array_equal(greedy.numpy(), hot.numpy())


def test_generate_cache_dtype_follows_params(tiny_gpt):
    import jax.numpy as jnp
    w = tiny_gpt.blocks[0].attn.qkv_proj.weight._data
    assert w.dtype == jnp.float32  # baseline assumption of this test
    # cast to bf16 and check generation still runs with bf16 caches
    tiny_gpt.to(dtype="bfloat16")
    try:
        ids = np.zeros((1, 3), np.int32)
        out = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=2)
        assert out.shape == [1, 5]
    finally:
        tiny_gpt.to(dtype="float32")


def test_data_feeder_mismatch_raises():
    from paddle_tpu.io import DataFeeder
    feeder = DataFeeder(feed_list=["x", "y"])
    with pytest.raises(ValueError):
        feeder.feed([(np.ones(3),), (np.zeros(3),)])


def test_fused_loss_matches_unfused(tiny_gpt):
    from paddle_tpu.models import GPTPretrainingCriterion
    rng = np.random.RandomState(1)
    ids = rng.randint(0, 128, (2, 8)).astype(np.int32)
    lab = rng.randint(0, 128, (2, 8)).astype(np.int32)
    tiny_gpt.fused_loss = True
    try:
        fused = tiny_gpt(paddle.to_tensor(ids),
                         labels=paddle.to_tensor(lab))
        tiny_gpt.fused_loss = False
        logits = tiny_gpt(paddle.to_tensor(ids))
        ref = GPTPretrainingCriterion()(logits, paddle.to_tensor(lab))
        assert float(fused.numpy()) == pytest.approx(float(ref.numpy()),
                                                     rel=1e-5)
    finally:
        tiny_gpt.fused_loss = False


def test_fused_loss_trains():
    from paddle_tpu.parallel.train_step import TrainStep
    from paddle_tpu import optimizer
    paddle.seed(3)
    m = GPTModel.from_config("tiny", dropout=0.0, fused_loss=True)
    rng = np.random.RandomState(3)
    ids = rng.randint(0, 128, (2, 8)).astype(np.int32)
    lab = rng.randint(0, 128, (2, 8)).astype(np.int32)
    step = TrainStep(m, optimizer.AdamW(learning_rate=1e-3,
                     parameters=m.parameters()), loss_fn=None)
    l0 = float(step.step([ids, lab]).numpy())
    for _ in range(8):
        l1 = float(step.step([ids, lab]).numpy())
    assert l1 < l0


def test_fused_loss_non_divisible_seq(tiny_gpt):
    from paddle_tpu.models import GPTPretrainingCriterion
    rng = np.random.RandomState(2)
    ids = rng.randint(0, 128, (1, 10)).astype(np.int32)   # 10 % 128 != 0
    lab = rng.randint(0, 128, (1, 10)).astype(np.int32)
    tiny_gpt.fused_loss = True
    try:
        fused = tiny_gpt(paddle.to_tensor(ids),
                         labels=paddle.to_tensor(lab))
        tiny_gpt.fused_loss = False
        ref = GPTPretrainingCriterion()(
            tiny_gpt(paddle.to_tensor(ids)), paddle.to_tensor(lab))
        assert float(fused.numpy()) == pytest.approx(float(ref.numpy()),
                                                     rel=1e-5)
    finally:
        tiny_gpt.fused_loss = False


def test_compiled_generate_matches_eager():
    """compiled=True (one jitted fixed-shape decode step) must produce
    exactly the eager KV-cache path's tokens."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTModel

    paddle.seed(0)
    model = GPTModel.from_config("tiny", dropout=0.0)
    ids = np.random.RandomState(0).randint(0, 128, (2, 7)).astype("int64")
    eager = model.generate(ids, max_new_tokens=9).numpy()
    comp = model.generate(ids, max_new_tokens=9, compiled=True).numpy()
    np.testing.assert_array_equal(eager, comp)
    # compiled sampling is deterministic under a fixed seed (exact
    # eager-vs-compiled token equality is NOT asserted for sampling:
    # the two differently-fused programs may differ in low-order bits,
    # which can flip a near-tie draw)
    s1 = model.generate(ids, max_new_tokens=6, top_k=5,
                        temperature=0.8, seed=11, compiled=True).numpy()
    n_cached = len(model._decode_fn_cache)
    s2 = model.generate(ids, max_new_tokens=6, top_k=5,
                        temperature=0.8, seed=11, compiled=True).numpy()
    np.testing.assert_array_equal(s1, s2)
    # the repeat call reused the cached jitted step (no new entry)
    assert len(model._decode_fn_cache) == n_cached


def test_generate_top_p_nucleus(tiny_gpt):
    """top_p < 1 filters to the nucleus: reproducible with a seed, and
    top_p ~ 0 degenerates to greedy (only the top token survives)."""
    ids = np.zeros((1, 3), np.int32)
    a = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6,
                          top_p=0.9, seed=7)
    b = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6,
                          top_p=0.9, seed=7)
    np.testing.assert_array_equal(a.numpy(), b.numpy())
    assert a.shape == [1, 9]
    greedy = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6)
    tiny_p = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6,
                               top_p=1e-6, seed=7)
    np.testing.assert_array_equal(tiny_p.numpy(), greedy.numpy())
    # top_p=0 (common 'greedy' convention) must also be top-1, not a
    # uniform sample over a fully-masked vocab
    zero_p = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6,
                               top_p=0.0, seed=7)
    np.testing.assert_array_equal(zero_p.numpy(), greedy.numpy())


@pytest.mark.slow
def test_generate_top_p_compiled_consistent(tiny_gpt):
    """top_p sampling works through the compiled decode path too and
    matches the eager path token-for-token (same seed, same filter)."""
    ids = np.zeros((2, 3), np.int32)
    eager = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6,
                              top_p=0.8, seed=11, compiled=False)
    comp = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6,
                             top_p=0.8, seed=11, compiled=True)
    np.testing.assert_array_equal(eager.numpy(), comp.numpy())


def test_fused_generate_matches_eager(tiny_gpt):
    """compiled="fused" (whole decode = one lax.scan jit, sampling on
    device) must produce exactly the eager KV-cache path's greedy tokens,
    and be deterministic under a fixed seed when sampling."""
    ids = np.random.RandomState(3).randint(0, 128, (2, 5)).astype("int32")
    eager = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=7)
    fused = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=7,
                              compiled="fused")
    np.testing.assert_array_equal(eager.numpy(), fused.numpy())
    s1 = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=5,
                           top_k=5, temperature=0.8, seed=11,
                           compiled="fused")
    n_cached = len(tiny_gpt._gen_fn_cache)
    s2 = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=5,
                           top_k=5, temperature=0.8, seed=11,
                           compiled="fused")
    np.testing.assert_array_equal(s1.numpy(), s2.numpy())
    # repeat call reused the cached whole-decode jit (no new entry)
    assert len(tiny_gpt._gen_fn_cache) == n_cached


def test_fused_generate_top_p_matches_eager(tiny_gpt):
    ids = np.zeros((2, 3), np.int32)
    eager = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6,
                              top_p=0.8, seed=11, compiled=False)
    fused = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=6,
                              top_p=0.8, seed=11, compiled="fused")
    np.testing.assert_array_equal(eager.numpy(), fused.numpy())


@pytest.mark.slow
def test_fused_generate_eos_truncation(tiny_gpt):
    """Fused decode truncates at the first all-rows-eos step exactly like
    the eager loop's break."""
    ids = np.random.RandomState(1).randint(0, 128, (2, 4)).astype("int32")
    ref = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=8)
    # pick the token the greedy path emits at step 2 for BOTH rows as a
    # fake eos: if the rows disagree no truncation happens — craft the
    # check from whatever the model actually emits
    ref_np = ref.numpy()
    step_cols = ref_np[:, 4:]
    eos = None
    for j in range(step_cols.shape[1]):
        if (step_cols[:, j] == step_cols[0, j]).all():
            eos = int(step_cols[0, j])
            break
    if eos is None:
        pytest.skip("greedy rows never agree on a token for this seed")
    eager = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=8,
                              eos_token_id=eos)
    fused = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=8,
                              eos_token_id=eos, compiled="fused")
    np.testing.assert_array_equal(eager.numpy(), fused.numpy())


def test_generate_zero_new_tokens(tiny_gpt):
    """max_new_tokens=0 returns the prompt unchanged on every path."""
    ids = np.random.RandomState(9).randint(0, 128, (2, 5)).astype("int32")
    for mode in (False, True, "fused"):
        out = tiny_gpt.generate(paddle.to_tensor(ids), max_new_tokens=0,
                                compiled=mode)
        np.testing.assert_array_equal(out.numpy(), ids)


class TestSpeculativeDecode:
    """compiled='speculative' (round 5): prompt-lookup drafting +
    windowed verify — bit-identical to fused greedy, fewer forwards
    when the model's own output repeats."""

    def test_exactness_vs_fused(self):
        paddle.seed(0)
        model = GPTModel.from_config("tiny", dropout=0.0,
                                     max_position=256)
        model.eval()
        rs = np.random.RandomState(0)
        for prompt in (rs.randint(0, 128, (1, 16)).astype(np.int32),
                       np.tile(np.array([5, 9, 17, 23], np.int32),
                               8)[None, :]):
            ref = model.generate(paddle.to_tensor(prompt),
                                 max_new_tokens=20,
                                 compiled="fused").numpy()
            spec = model.generate(paddle.to_tensor(prompt),
                                  max_new_tokens=20,
                                  compiled="speculative").numpy()
            np.testing.assert_array_equal(ref, spec)
            assert 1 <= model.last_spec_forwards <= 20

    def test_cyclic_model_accepts_drafts(self):
        """A model trained to emit a short cycle: speculation must
        cover max_new tokens in far fewer forwards (the whole point),
        while staying exactly greedy."""
        from paddle_tpu import optimizer
        from paddle_tpu.parallel.train_step import TrainStep
        paddle.seed(3)
        model = GPTModel.from_config("tiny", dropout=0.0,
                                     max_position=256)
        # teach it the cycle 11 -> 22 -> 33 -> 44 -> 11 ...
        cyc = np.tile(np.array([11, 22, 33, 44], np.int32), 16)
        x = cyc[None, :-1].copy()
        y = cyc[None, 1:].copy()
        step = TrainStep(model, optimizer.Adam(
            learning_rate=5e-3, parameters=model.parameters()),
            loss_fn=None)
        for _ in range(60):
            lv = float(step.step([x, y]).numpy())
        assert lv < 0.1, lv
        step.sync_to_layer()   # donated params -> back into the Layer
        model.eval()
        prompt = np.tile(np.array([11, 22, 33, 44], np.int32),
                         3)[None, :]
        ref = model.generate(paddle.to_tensor(prompt),
                             max_new_tokens=32,
                             compiled="fused").numpy()
        spec = model.generate(paddle.to_tensor(prompt),
                              max_new_tokens=32,
                              compiled="speculative",
                              draft_k=8).numpy()
        np.testing.assert_array_equal(ref, spec)
        # 32 tokens in <= ~32/4 forwards once drafts accept
        assert model.last_spec_forwards <= 10, \
            model.last_spec_forwards

    def test_sampled_speculative_reproducible(self):
        """do_sample speculative: exact conditional samples via
        per-position keys + equality acceptance — reproducible under a
        seed, valid token range, and still one-dispatch."""
        paddle.seed(5)
        model = GPTModel.from_config("tiny", dropout=0.0,
                                     max_position=256)
        model.eval()
        prompt = np.zeros((1, 8), np.int32)
        a = model.generate(paddle.to_tensor(prompt), max_new_tokens=16,
                           top_k=8, temperature=0.9, seed=7,
                           compiled="speculative").numpy()
        b = model.generate(paddle.to_tensor(prompt), max_new_tokens=16,
                           top_k=8, temperature=0.9, seed=7,
                           compiled="speculative").numpy()
        np.testing.assert_array_equal(a, b)
        assert a.shape == (1, 24)
        assert (a >= 0).all() and (a < 128).all()
        # a different seed gives a different trajectory (it samples)
        c = model.generate(paddle.to_tensor(prompt), max_new_tokens=16,
                           top_k=8, temperature=0.9, seed=8,
                           compiled="speculative").numpy()
        assert not np.array_equal(a, c)

    def test_batched_exactness_vs_fused(self):
        """B>1 synchronized advance: every row's output equals its
        fused-greedy trajectory even though rows accept at different
        rates."""
        paddle.seed(6)
        model = GPTModel.from_config("tiny", dropout=0.0,
                                     max_position=256)
        model.eval()
        rs = np.random.RandomState(6)
        prompts = np.concatenate(
            [np.tile(np.array([5, 9, 17, 23], np.int32), 4)[None, :],
             rs.randint(0, 128, (1, 16)).astype(np.int32),
             np.zeros((1, 16), np.int32)])
        ref = model.generate(paddle.to_tensor(prompts),
                             max_new_tokens=18,
                             compiled="fused").numpy()
        spec = model.generate(paddle.to_tensor(prompts),
                              max_new_tokens=18,
                              compiled="speculative").numpy()
        np.testing.assert_array_equal(ref, spec)

    def test_batched_sampling_reproducible(self):
        """B>1 with sampling (per-(row,position) keys + min-sync
        commit): seeded reproducibility, valid tokens, seed diversity,
        and rows differ from each other (independent key streams)."""
        paddle.seed(7)
        model = GPTModel.from_config("tiny", dropout=0.0,
                                     max_position=256)
        model.eval()
        prompts = np.zeros((3, 8), np.int32)
        kw = dict(max_new_tokens=16, top_k=8, temperature=0.9,
                  compiled="speculative")
        a = model.generate(paddle.to_tensor(prompts), seed=5,
                           **kw).numpy()
        b = model.generate(paddle.to_tensor(prompts), seed=5,
                           **kw).numpy()
        np.testing.assert_array_equal(a, b)
        assert a.shape == (3, 24)
        assert (a >= 0).all() and (a < 128).all()
        gen = a[:, 8:]
        # identical prompts, per-row keys: rows sample independently
        assert not (np.array_equal(gen[0], gen[1])
                    and np.array_equal(gen[1], gen[2]))
        c = model.generate(paddle.to_tensor(prompts), seed=6,
                           **kw).numpy()
        assert not np.array_equal(a, c)

    def test_guards(self):
        paddle.seed(0)
        model = GPTModel.from_config("tiny", dropout=0.0)
        model.eval()
        one = np.zeros((1, 8), np.int32)
        with pytest.raises(ValueError, match="max_position|draft_k"):
            model.generate(paddle.to_tensor(one), max_new_tokens=50,
                           compiled="speculative", draft_k=16)


def test_speculative_composes_with_weight_only_int8():
    """The full serving stack: weight-only int8 codes thread through
    the speculative while_loop as buffers (not baked constants), and
    int8 speculative greedy equals int8 fused greedy."""
    import jax.numpy as jnp
    from paddle_tpu.quantization import quantize_weights_int8
    paddle.seed(9)
    model = GPTModel.from_config("tiny", dropout=0.0, max_position=256)
    model.eval()
    quantize_weights_int8(model)
    ids = np.zeros((2, 12), np.int32)
    fused = model.generate(paddle.to_tensor(ids), max_new_tokens=12,
                           compiled="fused").numpy()
    spec = model.generate(paddle.to_tensor(ids), max_new_tokens=12,
                          compiled="speculative").numpy()
    np.testing.assert_array_equal(fused, spec)
    # buffers, not baked constants: mutate a quantized-code buffer and
    # the SAME cached executable must produce different tokens
    name, buf = next((n, b) for n, b in model.named_buffers()
                     if "int8" in str(b._data.dtype))
    rs = np.random.RandomState(0)
    buf._data = jnp.asarray(rs.randint(
        -127, 128, buf._data.shape).astype(np.int8))
    n_exec = len(model._spec_fn_cache)
    spec2 = model.generate(paddle.to_tensor(ids), max_new_tokens=12,
                           compiled="speculative").numpy()
    assert len(model._spec_fn_cache) == n_exec  # no retrace
    assert not np.array_equal(spec, spec2), name
