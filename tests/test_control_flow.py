"""Control flow: cond/while_loop/case/switch_case, eager and traced
(reference: test_cond.py, test_while_loop_op.py, test_case.py,
test_switch_case.py)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


def test_cond_eager():
    t = snn.cond(paddle.to_tensor(True), lambda: paddle.to_tensor(1.0),
                 lambda: paddle.to_tensor(2.0))
    assert float(t.numpy()) == 1.0
    f = snn.cond(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0),
                 lambda: paddle.to_tensor(2.0))
    assert float(f.numpy()) == 2.0


def test_cond_traced_both_branches():
    def f(x):
        t = paddle.Tensor(x)
        return snn.cond(t > 0, lambda: t * 2, lambda: t - 1)._data

    jf = jax.jit(f)
    assert float(jf(jnp.asarray(3.0))) == 6.0
    assert float(jf(jnp.asarray(-3.0))) == -4.0


def test_while_loop_eager_and_traced():
    vals = snn.while_loop(lambda i: i < 5, lambda i: i + 1,
                          [paddle.to_tensor(0)])
    assert int(vals[0].numpy()) == 5

    def g(n):
        vals = snn.while_loop(lambda i: i < 10, lambda i: i * 2,
                              [paddle.Tensor(n)])
        return vals[0]._data

    assert int(jax.jit(g)(jnp.asarray(3))) == 12


def test_while_loop_multiple_vars():
    i0 = paddle.to_tensor(0)
    s0 = paddle.to_tensor(0.0)
    i, s = snn.while_loop(lambda i, s: i < 4,
                          lambda i, s: (i + 1, s + 2.0), [i0, s0])
    assert int(i.numpy()) == 4
    assert float(s.numpy()) == 8.0


def test_case_and_switch_case():
    r = snn.case([(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0)),
                  (paddle.to_tensor(True), lambda: paddle.to_tensor(2.0))],
                 default=lambda: paddle.to_tensor(3.0))
    assert float(r.numpy()) == 2.0
    r2 = snn.switch_case(paddle.to_tensor(5),
                         {1: lambda: paddle.to_tensor(10.0),
                          5: lambda: paddle.to_tensor(50.0)},
                         default=lambda: paddle.to_tensor(-1.0))
    assert float(r2.numpy()) == 50.0
    # traced switch
    def h(i):
        return snn.switch_case(
            paddle.Tensor(i),
            {0: lambda: paddle.to_tensor(10.0),
             1: lambda: paddle.to_tensor(20.0)})._data
    assert float(jax.jit(h)(jnp.asarray(1))) == 20.0


def test_program_translator_shim():
    pt = paddle.jit.ProgramTranslator()
    assert pt is paddle.jit.ProgramTranslator.get_instance()
    pt.enable(False)
    assert not pt.enable_to_static
    pt.enable(True)
    assert pt.enable_to_static


# ---- regressions from code review ----------------------------------------

def test_switch_case_traced_nonmatching_goes_default():
    def h(i):
        return snn.switch_case(
            paddle.Tensor(i),
            {1: lambda: paddle.to_tensor(10.0),
             5: lambda: paddle.to_tensor(50.0)},
            default=lambda: paddle.to_tensor(-1.0))._data
    jh = jax.jit(h)
    assert float(jh(jnp.asarray(1))) == 10.0
    assert float(jh(jnp.asarray(5))) == 50.0
    assert float(jh(jnp.asarray(0))) == -1.0   # non-member -> default
    assert float(jh(jnp.asarray(2))) == -1.0


def test_switch_case_no_default_uses_last_branch():
    # reference: without default the last branch serves as default
    r = snn.switch_case(paddle.to_tensor(99),
                        {1: lambda: paddle.to_tensor(10.0),
                         5: lambda: paddle.to_tensor(50.0)})
    assert float(r.numpy()) == 50.0


def test_cond_traced_without_false_fn_raises():
    def f(x):
        t = paddle.Tensor(x)
        return snn.cond(t > 0, lambda: t * 2)
    with pytest.raises(ValueError):
        jax.jit(f)(jnp.asarray(1.0))


def test_case_traced_without_default_raises():
    def f(x):
        t = paddle.Tensor(x)
        return snn.case([(t > 0, lambda: t * 2)])
    with pytest.raises(ValueError):
        jax.jit(f)(jnp.asarray(1.0))


def test_program_translator_disable_runs_dygraph():
    from paddle_tpu import nn
    net = nn.Linear(2, 2)
    sf = paddle.jit.to_static(net)
    calls = []
    orig_forward = net.forward

    def spy(*a, **k):
        calls.append(1)
        return orig_forward(*a, **k)

    net.forward = spy
    paddle.jit.enable_to_static(False)
    try:
        x = paddle.to_tensor(np.ones((1, 2), "float32"))
        sf(x)
        assert calls  # dygraph forward ran directly
    finally:
        paddle.jit.enable_to_static(True)
        net.forward = orig_forward
