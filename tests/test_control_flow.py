"""Control flow: cond/while_loop/case/switch_case, eager and traced
(reference: test_cond.py, test_while_loop_op.py, test_case.py,
test_switch_case.py)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.static import nn as snn


def test_cond_eager():
    t = snn.cond(paddle.to_tensor(True), lambda: paddle.to_tensor(1.0),
                 lambda: paddle.to_tensor(2.0))
    assert float(t.numpy()) == 1.0
    f = snn.cond(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0),
                 lambda: paddle.to_tensor(2.0))
    assert float(f.numpy()) == 2.0


def test_cond_traced_both_branches():
    def f(x):
        t = paddle.Tensor(x)
        return snn.cond(t > 0, lambda: t * 2, lambda: t - 1)._data

    jf = jax.jit(f)
    assert float(jf(jnp.asarray(3.0))) == 6.0
    assert float(jf(jnp.asarray(-3.0))) == -4.0


def test_while_loop_eager_and_traced():
    vals = snn.while_loop(lambda i: i < 5, lambda i: i + 1,
                          [paddle.to_tensor(0)])
    assert int(vals[0].numpy()) == 5

    def g(n):
        vals = snn.while_loop(lambda i: i < 10, lambda i: i * 2,
                              [paddle.Tensor(n)])
        return vals[0]._data

    assert int(jax.jit(g)(jnp.asarray(3))) == 12


def test_while_loop_multiple_vars():
    i0 = paddle.to_tensor(0)
    s0 = paddle.to_tensor(0.0)
    i, s = snn.while_loop(lambda i, s: i < 4,
                          lambda i, s: (i + 1, s + 2.0), [i0, s0])
    assert int(i.numpy()) == 4
    assert float(s.numpy()) == 8.0


def test_case_and_switch_case():
    r = snn.case([(paddle.to_tensor(False), lambda: paddle.to_tensor(1.0)),
                  (paddle.to_tensor(True), lambda: paddle.to_tensor(2.0))],
                 default=lambda: paddle.to_tensor(3.0))
    assert float(r.numpy()) == 2.0
    r2 = snn.switch_case(paddle.to_tensor(5),
                         {1: lambda: paddle.to_tensor(10.0),
                          5: lambda: paddle.to_tensor(50.0)},
                         default=lambda: paddle.to_tensor(-1.0))
    assert float(r2.numpy()) == 50.0
    # traced switch
    def h(i):
        return snn.switch_case(
            paddle.Tensor(i),
            {0: lambda: paddle.to_tensor(10.0),
             1: lambda: paddle.to_tensor(20.0)})._data
    assert float(jax.jit(h)(jnp.asarray(1))) == 20.0


def test_program_translator_shim():
    pt = paddle.jit.ProgramTranslator()
    assert pt is paddle.jit.ProgramTranslator.get_instance()
    pt.enable(False)
    assert not pt.enable_to_static
    pt.enable(True)
    assert pt.enable_to_static


# ---- regressions from code review ----------------------------------------

def test_switch_case_traced_nonmatching_goes_default():
    def h(i):
        return snn.switch_case(
            paddle.Tensor(i),
            {1: lambda: paddle.to_tensor(10.0),
             5: lambda: paddle.to_tensor(50.0)},
            default=lambda: paddle.to_tensor(-1.0))._data
    jh = jax.jit(h)
    assert float(jh(jnp.asarray(1))) == 10.0
    assert float(jh(jnp.asarray(5))) == 50.0
    assert float(jh(jnp.asarray(0))) == -1.0   # non-member -> default
    assert float(jh(jnp.asarray(2))) == -1.0


def test_switch_case_no_default_uses_last_branch():
    # reference: without default the last branch serves as default
    r = snn.switch_case(paddle.to_tensor(99),
                        {1: lambda: paddle.to_tensor(10.0),
                         5: lambda: paddle.to_tensor(50.0)})
    assert float(r.numpy()) == 50.0


def test_cond_traced_without_false_fn_raises():
    def f(x):
        t = paddle.Tensor(x)
        return snn.cond(t > 0, lambda: t * 2)
    with pytest.raises(ValueError):
        jax.jit(f)(jnp.asarray(1.0))


def test_case_traced_without_default_raises():
    def f(x):
        t = paddle.Tensor(x)
        return snn.case([(t > 0, lambda: t * 2)])
    with pytest.raises(ValueError):
        jax.jit(f)(jnp.asarray(1.0))


def test_program_translator_disable_runs_dygraph():
    from paddle_tpu import nn
    net = nn.Linear(2, 2)
    sf = paddle.jit.to_static(net)
    calls = []
    orig_forward = net.forward

    def spy(*a, **k):
        calls.append(1)
        return orig_forward(*a, **k)

    net.forward = spy
    paddle.jit.enable_to_static(False)
    try:
        x = paddle.to_tensor(np.ones((1, 2), "float32"))
        sf(x)
        assert calls  # dygraph forward ran directly
    finally:
        paddle.jit.enable_to_static(True)
        net.forward = orig_forward


class TestCondInProgram:
    """static.nn.cond inside a RECORDED Program (round 5, VERDICT r4
    weak-#6): branch sub-graphs are lifted into one fused lax.cond
    OpNode — the conditional_block analogue without sub-blocks."""

    def _run(self, build, feeds):
        import paddle_tpu as paddle
        from paddle_tpu import static
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                fetch = build()
                exe = static.Executor()
                return [exe.run(main, feed=f, fetch_list=[fetch])[0]
                        for f in feeds]
        finally:
            paddle.disable_static()

    def test_branch_selection_and_params(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import static
        xv = np.random.RandomState(0).rand(4, 3).astype("float32")

        def build():
            x = static.data("x", [4, 3])
            flag = static.data("flag", [1], dtype="int32")
            h = static.nn.fc(x, 5, activation="relu")
            return static.nn.cond(
                flag,
                lambda: paddle.scale(h, 2.0),
                lambda: paddle.scale(h, -1.0))

        r1, r0 = self._run(build, [
            {"x": xv, "flag": np.array([1], np.int32)},
            {"x": xv, "flag": np.array([0], np.int32)}])
        np.testing.assert_allclose(np.asarray(r1), -2.0 * np.asarray(r0),
                                   rtol=1e-5)

    def test_nested_cond_and_passthrough(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import static

        def build():
            x = static.data("x", [2, 2])
            a = static.data("a", [1], dtype="int32")
            b = static.data("b", [1], dtype="int32")
            return static.nn.cond(
                a,
                lambda: static.nn.cond(b,
                                       lambda: paddle.scale(x, 4.0),
                                       lambda: paddle.scale(x, 3.0)),
                lambda: x)  # pass-through of an OUTER variable

        xv = np.ones((2, 2), np.float32)
        outs = self._run(build, [
            {"x": xv, "a": np.array([1], np.int32),
             "b": np.array([1], np.int32)},
            {"x": xv, "a": np.array([1], np.int32),
             "b": np.array([0], np.int32)},
            {"x": xv, "a": np.array([0], np.int32),
             "b": np.array([1], np.int32)}])
        assert float(np.asarray(outs[0])[0, 0]) == 4.0
        assert float(np.asarray(outs[1])[0, 0]) == 3.0
        assert float(np.asarray(outs[2])[0, 0]) == 1.0

    def test_mismatched_branches_raise(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import static
        import pytest
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data("x", [2, 2])
                f = static.data("f", [1], dtype="int32")
                with pytest.raises((ValueError, TypeError)):
                    static.nn.cond(
                        f,
                        lambda: (paddle.scale(x, 1.0),
                                 paddle.scale(x, 2.0)),
                        lambda: paddle.scale(x, 3.0))
        finally:
            paddle.disable_static()


class TestWhileInProgram:
    """static.nn.while_loop inside a RECORDED Program (round 5): the
    cond/body spans lift into one fused lax.while_loop OpNode; eager
    loop vars get symbolic carry stand-ins so the carry actually feeds
    back (the silent-constant-carry hang this round fixed)."""

    def test_data_dependent_trip_count(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import static
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                n = static.data("n", [1], dtype="int32")
                x = static.data("x", [2], dtype="float32")
                i, acc = static.nn.while_loop(
                    lambda i, acc: paddle.less_than(i, n),
                    lambda i, acc: [i + paddle.ones([1], "int32"),
                                    acc + x],
                    [paddle.zeros([1], dtype="int32"),
                     paddle.zeros([2], dtype="float32")])
                exe = static.Executor()
                xv = np.array([1.5, 2.0], np.float32)
                for trips in (4, 7, 1, 0):
                    iv, av = exe.run(
                        main,
                        feed={"n": np.array([trips], np.int32),
                              "x": xv},
                        fetch_list=[i, acc])
                    assert int(np.asarray(iv)[0]) == trips
                    np.testing.assert_allclose(np.asarray(av),
                                               trips * xv, rtol=1e-6)
        finally:
            paddle.disable_static()

    def test_symbolic_bool_raises(self):
        """Variable truthiness raises instead of silently looping
        forever (the hang's root cause)."""
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu import static
        import pytest
        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                f = static.data("f", [1], dtype="int32")
                with pytest.raises(TypeError, match="symbolic"):
                    bool(f)
        finally:
            paddle.disable_static()


def test_case_and_switch_case_in_program():
    """case/switch_case inside a recorded Program route through the
    record-capable cond chain (round 5)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import static
    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2])
            sel = static.data("sel", [1], dtype="int32")
            sw = static.nn.switch_case(
                sel, {0: lambda: paddle.scale(x, 1.0),
                      2: lambda: paddle.scale(x, 2.0),
                      5: lambda: paddle.scale(x, 5.0)})
            big = static.nn.case(
                [(paddle.greater_than(paddle.sum(x),
                                      paddle.to_tensor(10.0)),
                  lambda: paddle.scale(x, 100.0))],
                default=lambda: x)
            exe = static.Executor()
            ones = np.ones((2, 2), np.float32)
            for s_, want in ((0, 1.0), (2, 2.0), (5, 5.0), (7, 5.0)):
                v, = exe.run(main,
                             feed={"x": ones,
                                   "sel": np.array([s_], np.int32)},
                             fetch_list=[sw])
                assert float(np.asarray(v)[0, 0]) == want
            v_small, = exe.run(main, feed={"x": ones,
                                           "sel": np.array([0],
                                                           np.int32)},
                               fetch_list=[big])
            assert float(np.asarray(v_small)[0, 0]) == 1.0
            v_big, = exe.run(main,
                             feed={"x": ones * 5,
                                   "sel": np.array([0], np.int32)},
                             fetch_list=[big])
            assert float(np.asarray(v_big)[0, 0]) == 500.0
    finally:
        paddle.disable_static()
