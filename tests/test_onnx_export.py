"""paddle.onnx.export (round 5, VERDICT r4 #9): real minimal ONNX
artifacts for the zoo models, validated NUMERICALLY by executing the
emitted graph with an independent torch-based evaluator (no onnx
package in this environment — the evaluator reads the protobuf we
wrote and re-implements each emitted op with torch/numpy)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


def _load(path):
    from paddle_tpu.onnx_export import onnx_subset_pb2 as P
    m = P.ModelProto()
    with open(path, "rb") as f:
        m.ParseFromString(f.read())
    return m


_NP_OF = {1: np.float32, 3: np.int8, 6: np.int32, 7: np.int64,
          9: np.bool_, 11: np.float64}


def _tensor_value(t):
    arr = np.frombuffer(t.raw_data, dtype=_NP_OF[t.data_type])
    return arr.reshape(list(t.dims)).copy()


def _run_onnx(model, feeds):
    """Execute the emitted graph with torch (independent of jax)."""
    import torch
    import torch.nn.functional as TF

    env = {}
    for t in model.graph.initializer:
        env[t.name] = torch.from_numpy(_tensor_value(t))
    for vi, arr in zip(model.graph.input, feeds):
        env[vi.name] = torch.from_numpy(np.asarray(arr))

    def attr(nd, name, default=None):
        for a in nd.attribute:
            if a.name == name:
                if a.type == 7:      # INTS
                    return list(a.ints)
                if a.type == 2:      # INT
                    return int(a.i)
                if a.type == 1:      # FLOAT
                    return float(a.f)
        return default

    for nd in model.graph.node:
        i = [env[x] for x in nd.input]
        op = nd.op_type
        if op == "Conv":
            pads = attr(nd, "pads")
            assert pads[0] == pads[2] and pads[1] == pads[3], pads
            o = TF.conv2d(i[0], i[1], None,
                          stride=attr(nd, "strides"),
                          padding=pads[:2],
                          dilation=attr(nd, "dilations"),
                          groups=attr(nd, "group", 1))
        elif op == "MaxPool":
            pads = attr(nd, "pads")
            o = TF.max_pool2d(i[0], attr(nd, "kernel_shape"),
                              stride=attr(nd, "strides"),
                              padding=pads[:2])
        elif op == "AveragePool":
            pads = attr(nd, "pads")
            o = TF.avg_pool2d(i[0], attr(nd, "kernel_shape"),
                              stride=attr(nd, "strides"),
                              padding=pads[:2],
                              count_include_pad=True)
        elif op == "MatMul":
            o = i[0] @ i[1]
        elif op == "Add":
            o = i[0] + i[1]
        elif op == "Sub":
            o = i[0] - i[1]
        elif op == "Mul":
            o = i[0] * i[1]
        elif op == "Div":
            o = i[0] / i[1]
        elif op == "Max":
            o = torch.maximum(i[0], i[1])
        elif op == "Min":
            o = torch.minimum(i[0], i[1])
        elif op == "Sqrt":
            o = torch.sqrt(i[0])
        elif op == "Pow":
            o = torch.pow(i[0], i[1])
        elif op == "Exp":
            o = torch.exp(i[0])
        elif op == "Sigmoid":
            o = torch.sigmoid(i[0])
        elif op == "Tanh":
            o = torch.tanh(i[0])
        elif op == "Reciprocal":
            o = 1.0 / i[0]
        elif op == "Greater":
            o = i[0] > i[1]
        elif op == "Less":
            o = i[0] < i[1]
        elif op == "GreaterOrEqual":
            o = i[0] >= i[1]
        elif op == "LessOrEqual":
            o = i[0] <= i[1]
        elif op == "Equal":
            o = i[0] == i[1]
        elif op == "Not":
            o = ~i[0]
        elif op == "And":
            o = i[0] & i[1]
        elif op == "Or":
            o = i[0] | i[1]
        elif op == "Xor":
            o = i[0] ^ i[1]
        elif op == "Neg":
            o = -i[0]
        elif op == "Erf":
            o = torch.erf(i[0])
        elif op == "Gather":
            o = i[0].index_select(
                attr(nd, "axis", 0),
                i[1].reshape(-1)).reshape(
                    tuple(i[1].shape) + tuple(i[0].shape[1:]))
        elif op == "Where":
            o = torch.where(i[0], i[1], i[2])
        elif op == "Reshape":
            o = i[0].reshape([int(v) for v in i[1]])
        elif op == "Expand":
            o = i[0].expand([int(v) for v in i[1]])
        elif op == "Transpose":
            o = i[0].permute(attr(nd, "perm"))
        elif op == "Concat":
            o = torch.cat(i, dim=attr(nd, "axis"))
        elif op == "ReduceSum":
            o = i[0].sum(dim=[int(v) for v in i[1]])
        elif op == "ReduceMax":
            o = torch.amax(i[0], dim=attr(nd, "axes"))
        elif op == "Cast":
            to = attr(nd, "to")
            o = i[0].to(dict(
                {1: torch.float32, 6: torch.int32, 7: torch.int64,
                 9: torch.bool})[to])
        elif op == "Identity":
            o = i[0]
        elif op == "Slice":
            starts, ends, axes, steps = (
                [int(v) for v in x] for x in i[1:5])
            o = i[0]
            for s, e, ax, st in zip(starts, ends, axes, steps):
                o = o.index_select(
                    ax, torch.arange(s, min(e, o.shape[ax]), st))
        else:
            raise AssertionError(f"evaluator: unmapped op {op}")
        env[nd.output[0]] = o
    return [env[vo.name].numpy() for vo in model.graph.output]


def _export_and_compare(net, shape, tmp_path, name, atol=1e-4):
    net.eval()
    x = np.random.RandomState(0).rand(*shape).astype("float32")
    golden = net(paddle.to_tensor(x)).numpy()
    path = paddle.onnx.export(
        net, str(tmp_path / name),
        input_spec=[static.InputSpec(list(shape), "float32")])
    model = _load(path)
    assert model.ir_version == 7
    assert model.opset_import[0].version == 13
    out, = _run_onnx(model, [x])
    np.testing.assert_allclose(out, golden, rtol=1e-3, atol=atol)
    return model


def test_lenet_onnx_numerics(tmp_path):
    from paddle_tpu.vision.models import LeNet
    paddle.seed(0)
    m = _export_and_compare(LeNet(num_classes=10), (2, 1, 28, 28),
                            tmp_path, "lenet")
    ops = {n.op_type for n in m.graph.node}
    assert {"Conv", "MaxPool", "MatMul"} <= ops


@pytest.mark.slow
def test_resnet18_onnx_numerics(tmp_path):
    from paddle_tpu.vision.models import resnet18
    paddle.seed(1)
    m = _export_and_compare(resnet18(num_classes=10), (1, 3, 32, 32),
                            tmp_path, "resnet18", atol=5e-4)
    assert len(m.graph.node) > 50


def test_mlp_softmax_onnx(tmp_path):
    from paddle_tpu import nn
    paddle.seed(2)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4),
                        nn.Softmax())
    _export_and_compare(net, (4, 8), tmp_path, "mlp")


def test_gpt_onnx_numerics(tmp_path):
    """Transformer coverage (round 5 extension): GPT lowers through
    general dot_general (attention einsums -> transpose/reshape/batched
    MatMul) and embedding gathers; numerics must match eager."""
    from paddle_tpu.models import GPTModel
    paddle.seed(3)
    model = GPTModel.from_config("tiny")
    model.eval()
    ids = np.random.RandomState(3).randint(
        0, 128, (2, 12)).astype(np.int64)
    golden = model(paddle.to_tensor(ids)).numpy()
    path = paddle.onnx.export(
        model, str(tmp_path / "gpt"),
        input_spec=[static.InputSpec([2, 12], "int64")])
    out, = _run_onnx(_load(path), [ids])
    np.testing.assert_allclose(out, golden, rtol=1e-3, atol=2e-4)
    assert (out.argmax(-1) == golden.argmax(-1)).all()


@pytest.mark.slow
def test_bert_onnx_numerics(tmp_path):
    from paddle_tpu.models.bert import BertModel
    paddle.seed(4)
    model = BertModel.from_config("tiny")
    model.eval()
    ids = np.random.RandomState(4).randint(
        0, 128, (2, 10)).astype(np.int64)
    golden = model(paddle.to_tensor(ids))[0].numpy()

    class SeqOut(paddle.nn.Layer):
        def __init__(self, m):
            super().__init__()
            self.m = m

        def forward(self, x):
            return self.m(x)[0]

    path = paddle.onnx.export(
        SeqOut(model), str(tmp_path / "bert"),
        input_spec=[static.InputSpec([2, 10], "int64")])
    out, = _run_onnx(_load(path), [ids])
    np.testing.assert_allclose(out, golden, rtol=1e-3, atol=2e-4)


def test_avgpool_scale_matches_tensor_dtype(tmp_path):
    """ADVICE low: reduce_window_sum's AveragePool rescale constant
    must carry the TENSOR dtype — a float32 scalar in a float64 graph
    makes the Mul operands mismatch (invalid model, no export error)."""
    from paddle_tpu import nn

    class SumPool(nn.Layer):
        def forward(self, x):
            # lowers through reduce_window_sum (+ div by the count)
            return paddle.nn.functional.avg_pool2d(
                x, kernel_size=2, stride=2)

    # float64 would silently trace as float32 (jax x64 off), so the
    # narrow/wide pair here is float16 vs float32
    for dtype, want in (("float32", 1), ("float16", 10)):
        path = paddle.onnx.export(
            SumPool(), str(tmp_path / f"sp_{dtype}"),
            input_spec=[static.InputSpec([1, 1, 4, 4], dtype)])
        m = _load(path)
        muls = [n for n in m.graph.node if n.op_type == "Mul"]
        assert muls, "expected the AveragePool rescale Mul"
        inits = {t.name: t for t in m.graph.initializer}
        scale_dts = [inits[x].data_type for n in muls for x in n.input
                     if x in inits]
        assert scale_dts and all(dt == want for dt in scale_dts), \
            (dtype, scale_dts)


def test_initializer_dedup(tmp_path):
    """ADVICE low: unnamed constants are memoized by (dtype, shape,
    bytes) — a graph repeating the same shape vector / scalar emits ONE
    initializer, not one per use."""
    from paddle_tpu import nn

    class TwiceReshaped(nn.Layer):
        def forward(self, x):
            a = paddle.reshape(x, [2, 6]) * 2.0
            b = paddle.reshape(x, [2, 6]) * 2.0  # same shape + scalar
            return paddle.reshape(a + b, [12])

    path = paddle.onnx.export(
        TwiceReshaped(), str(tmp_path / "dedup"),
        input_spec=[static.InputSpec([3, 4], "float32")])
    m = _load(path)
    seen = {}
    for t in m.graph.initializer:
        key = (t.data_type, tuple(t.dims), t.raw_data)
        assert key not in seen, \
            f"duplicate initializer: {t.name} == {seen[key]}"
        seen[key] = t.name


def test_dynamic_dims_guided(tmp_path):
    from paddle_tpu import nn
    with pytest.raises(ValueError, match="StableHLO"):
        paddle.onnx.export(nn.Linear(4, 2), str(tmp_path / "d"),
                           input_spec=[static.InputSpec([None, 4],
                                                        "float32")])


def test_unmapped_primitive_guided(tmp_path):
    from paddle_tpu import nn

    class Sorty(nn.Layer):
        def forward(self, x):
            return paddle.sort(x)

    with pytest.raises(NotImplementedError, match="StableHLO"):
        paddle.onnx.export(Sorty(), str(tmp_path / "s"),
                           input_spec=[static.InputSpec([4, 4],
                                                        "float32")])


def test_logical_ops_onnx(tmp_path):
    """bool And/Or/Xor/Not export end-to-end; bitwise int forms keep
    the guided raise (ONNX logical ops are bool-only)."""
    from paddle_tpu import nn

    class Logic(nn.Layer):
        def forward(self, x):
            a = x > 0.5
            b = x < 0.8
            both = paddle.logical_and(a, b)
            either = paddle.logical_or(a, b)
            odd = paddle.logical_xor(a, b)
            keep = paddle.logical_and(paddle.logical_not(odd), either)
            return paddle.cast(both, "float32") \
                + paddle.cast(keep, "float32")

    _export_and_compare(Logic(), (3, 5), tmp_path, "logic")

    class BitwiseInt(nn.Layer):
        def forward(self, x):
            xi = paddle.cast(x, "int32")
            return paddle.bitwise_and(xi, xi)

    with pytest.raises(NotImplementedError, match="bool-only"):
        paddle.onnx.export(BitwiseInt(), str(tmp_path / "bw"),
                           input_spec=[static.InputSpec([2, 2],
                                                        "float32")])


@pytest.mark.slow
def test_mobilenet_v2_onnx_numerics(tmp_path):
    """Depthwise (grouped) convolutions + inverted residuals."""
    from paddle_tpu.vision.models import mobilenet_v2
    paddle.seed(5)
    m = _export_and_compare(mobilenet_v2(num_classes=10),
                            (1, 3, 32, 32), tmp_path, "mbv2",
                            atol=5e-4)
    groups = [a.i for n in m.graph.node if n.op_type == "Conv"
              for a in n.attribute if a.name == "group"]
    assert any(g > 1 for g in groups)  # depthwise convs exported
