"""Deterministic fault injection + chaos storms (serving/faults.py).

The injector's schedule is a pure function of (seed, site, tick) —
asserted directly — and the chaos storms drive the FULL-FEATURE engine
(paged + chunked + speculative + async depth 2 + priorities/preemption)
against a seeded multi-failure schedule, then assert the whole
recovery-invariant set: every waiter unblocked, pool refcounts at
zero, no cross-slot stream corruption (greedy survivors are
token-identical to generate()), the async ring empty, and the SAME
SEED reproducing the same fault schedule, the same error sequence,
and the same per-request outcomes.  All CPU, tiny model; the short
storm is tier-1 (``chaos`` marker), the long one also ``slow``.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.models import GPTModel
from paddle_tpu.serving import (AdapterInUse, Engine, FaultInjector,
                                InjectedFault, LoRAAdapter,
                                NoFreeBlocks, PromptLookupProposer,
                                TokenStream, WatchdogTimeout)
from paddle_tpu.serving.engine import Migrated
from paddle_tpu.serving.faults import (SITES, NetDisconnect,
                                       StreamDisconnect)


@pytest.fixture(scope="module")
def tiny_gpt():
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0)
    m.eval()
    return m


def _prompts(lens=(5, 9, 12, 7, 16, 4)):
    rng = np.random.RandomState(3)
    return [rng.randint(0, 128, (l,)).astype(np.int32) for l in lens]


# ---------------------------------------------------------------------------
# injector unit behavior
# ---------------------------------------------------------------------------

def test_injector_schedule_is_pure_and_seeded():
    """scheduled(site, tick) is a pure function of (seed, site, tick):
    re-querying never changes it, equal seeds agree everywhere,
    different seeds diverge somewhere, and rates 0/1 are exact."""
    a = FaultInjector(seed=7, rates={"dispatch": 0.3})
    b = FaultInjector(seed=7, rates={"dispatch": 0.3})
    c = FaultInjector(seed=8, rates={"dispatch": 0.3})
    sched_a = [a.scheduled("dispatch", t) for t in range(200)]
    assert sched_a == [a.scheduled("dispatch", t) for t in range(200)]
    assert sched_a == [b.scheduled("dispatch", t) for t in range(200)]
    assert sched_a != [c.scheduled("dispatch", t) for t in range(200)]
    n = sum(sched_a)
    assert 20 <= n <= 100, f"rate 0.3 fired {n}/200 — hash is biased"
    # sites are independent streams off one seed
    assert ([a.scheduled("dispatch", t) for t in range(200)]
            != [FaultInjector(seed=7, rates={"d2h_hang": 0.3})
                .scheduled("d2h_hang", t) for t in range(200)])
    always = FaultInjector(seed=0, rates={"host_slow": 1.0})
    never = FaultInjector(seed=0, rates={})
    assert all(always.scheduled("host_slow", t) for t in range(50))
    assert not any(never.scheduled(s, t)
                   for s in SITES for t in range(50))


def test_injector_explicit_window_and_validation():
    inj = FaultInjector(seed=0, rates={"dispatch": 1.0},
                        first_tick=10, last_tick=20)
    assert not inj.scheduled("dispatch", 9)
    assert inj.scheduled("dispatch", 10)
    assert inj.scheduled("dispatch", 20)
    assert not inj.scheduled("dispatch", 21)
    inj.at(3, "dispatch")               # explicit beats the window
    assert inj.scheduled("dispatch", 3)
    with pytest.raises(ValueError):
        inj.at(1, "nope")
    with pytest.raises(ValueError):
        FaultInjector(rates={"bogus_site": 0.5})
    with pytest.raises(InjectedFault):
        inj.fire("dispatch", 3)
    assert inj.log == [(3, "dispatch")]  # recorded before the raise


# ---------------------------------------------------------------------------
# single-site behavior through the engine
# ---------------------------------------------------------------------------

def test_dispatch_fault_recovers_engine(tiny_gpt):
    """An injected dispatch failure lands in the existing step-failure
    recovery: in-flight waiters unblock with errors, the engine and
    pool rebuild, and later requests decode to parity."""
    inj = FaultInjector(seed=0).at(2, "dispatch")
    eng = Engine(tiny_gpt, num_slots=2, max_seq_len=48,
                 kv_block_size=8, registry=monitor.StatRegistry(),
                 faults=inj)
    p = _prompts()[0]
    doomed = eng.submit(p, max_new_tokens=8)
    eng.step()                     # tick 1: admit + first token
    with pytest.raises(InjectedFault):
        eng.step()                 # tick 2: injected dispatch raise
    assert doomed.done() and doomed.error is not None
    assert inj.log == [(2, "dispatch")]
    assert eng.registry.get("serving.faults_injected").value == 1
    ok = eng.submit(p, max_new_tokens=6)
    eng.run_until_idle()
    ref = tiny_gpt.generate(paddle.to_tensor(p[None, :]),
                            max_new_tokens=6).numpy()[0]
    np.testing.assert_array_equal(ok.result(timeout=1), ref)


def test_pool_exhaust_fault_requeues_popped_request(tiny_gpt):
    """Regression: a gate that RAISES mid-reservation (injected pool
    exhaustion) must not LOSE the popped request — it returns to the
    queue head, survives the recovery, and completes on a later
    tick."""
    inj = FaultInjector(seed=0).at(3, "pool_exhaust")
    eng = Engine(tiny_gpt, num_slots=2, max_seq_len=48,
                 kv_block_size=8, registry=monitor.StatRegistry(),
                 faults=inj)
    p = _prompts()[0]
    eng.step()                     # tick 1 idle
    eng.step()                     # tick 2 idle
    survivor = eng.submit(p, max_new_tokens=6)
    with pytest.raises(NoFreeBlocks):
        eng.step()                 # tick 3: alloc raises at the gate
    assert not survivor.done()     # still queued, NOT lost
    assert eng.queue.depth() == 1
    eng.run_until_idle()
    ref = tiny_gpt.generate(paddle.to_tensor(p[None, :]),
                            max_new_tokens=6).numpy()[0]
    np.testing.assert_array_equal(survivor.result(timeout=1), ref)
    assert eng.block_pool.in_use() >= 0  # pool consistent
    eng.prefix_cache.clear()
    assert eng.block_pool.in_use() == 0


def test_watchdog_converts_wedged_d2h_to_recovery(tiny_gpt):
    """A wedged consume (injected d2h hang far longer than the
    watchdog) is flight-recorded by the watchdog thread and converted
    into a WatchdogTimeout raise -> step recovery: waiters unblock,
    the engine serves on."""
    inj = FaultInjector(seed=0, hang_s=5.0).at(3, "d2h_hang")
    eng = Engine(tiny_gpt, num_slots=2, max_seq_len=48,
                 registry=monitor.StatRegistry(), faults=inj,
                 watchdog_s=0.05)
    p = _prompts()[0]
    doomed = eng.submit(p, max_new_tokens=8)
    raised = None
    for _ in range(6):
        try:
            eng.step()
        except WatchdogTimeout as e:
            raised = e
            break
    assert raised is not None, "watchdog never converted the hang"
    assert doomed.done() and doomed.error is not None
    assert eng.registry.get("serving.watchdog_fires").value >= 1
    # the watchdog's dump (or the recovery's, which overwrites it)
    # exists and names the wedge context
    assert eng.last_flight is not None
    meta = eng.last_flight["metadata"]["flight-recorder"]
    assert "preemptions" in meta
    ok = eng.submit(p, max_new_tokens=4)
    eng.run_until_idle()
    ref = tiny_gpt.generate(paddle.to_tensor(p[None, :]),
                            max_new_tokens=4).numpy()[0]
    np.testing.assert_array_equal(ok.result(timeout=1), ref)
    eng.stop()


def test_proposer_failure_degrades_not_fails(tiny_gpt):
    """A raising proposer degrades to zero drafts (plain decode
    speed): no eviction, greedy parity preserved, failures counted."""

    class FlakyProposer(PromptLookupProposer):
        def __init__(self):
            super().__init__()
            self.calls = 0

        def propose(self, history, k):
            self.calls += 1
            if self.calls % 2:
                raise RuntimeError("draft backend down")
            return super().propose(history, k)

    prop = FlakyProposer()
    eng = Engine(tiny_gpt, num_slots=2, max_seq_len=48, spec_k=2,
                 proposer=prop, registry=monitor.StatRegistry())
    p = _prompts()[0]
    r = eng.submit(p, max_new_tokens=8)
    eng.run_until_idle()
    ref = tiny_gpt.generate(paddle.to_tensor(p[None, :]),
                            max_new_tokens=8).numpy()[0]
    np.testing.assert_array_equal(r.result(timeout=1), ref)
    assert eng.registry.get("serving.proposer_failures").value >= 1
    assert prop.calls >= 2


# ---------------------------------------------------------------------------
# chaos storms
# ---------------------------------------------------------------------------

def _storm(model, seed, ticks, refs):
    """One seeded storm over the full-feature engine.  Returns the
    reproducibility signature (fault log, per-request outcomes, error
    sequence) after asserting the invariant set."""
    inj = FaultInjector(
        seed=seed,
        rates={"dispatch": 0.04, "d2h_hang": 0.03,
               "pool_exhaust": 0.03, "host_slow": 0.05,
               "spec_draft": 0.08},
        hang_s=0.5, slow_s=0.002,
        # the first storm ticks stay fault-free so the scripted
        # priority burst below exercises preemption deterministically;
        # nothing fires past the window so the engine drains clean
        first_tick=12, last_tick=ticks)
    eng = Engine(model, num_slots=3, max_seq_len=64, kv_block_size=8,
                 prefill_chunk=8, tick_token_budget=16, spec_k=2,
                 async_depth=2, watchdog_s=0.04,
                 registry=monitor.StatRegistry())
    prompts = _prompts()
    for i in range(3):             # warm every compile shape
        eng.submit(prompts[i], max_new_tokens=2)
    eng.run_until_idle()
    warm_ticks = eng.tick_no
    inj.first_tick += warm_ticks
    inj.last_tick += warm_ticks
    eng.faults = inj
    # scripted mixed traffic: greedy + seeded sampling, background
    # (pri 0) + interactive (pri 3..7) — the t=2 burst lands while all
    # three slots hold pri-0 streams, forcing a preemption before any
    # fault fires
    sched = {
        0: [(0, 12, 0, None), (1, 10, 0, None), (2, 12, 0, None)],
        2: [(3, 6, 5, None)],
        8: [(4, 8, 0, 42)],
        14: [(5, 10, 3, None)],
        22: [(0, 8, 0, None), (1, 6, 7, None)],
        30: [(2, 8, 0, None)],
    }
    reqs, errors = [], []
    for t in range(ticks):
        for (pi, mn, pri, sd) in sched.get(t, []):
            kw = ({} if sd is None else
                  {"temperature": 0.9, "top_p": 0.9, "seed": sd})
            reqs.append((pi, mn, sd,
                         eng.submit(prompts[pi], max_new_tokens=mn,
                                    priority=pri, **kw)))
        try:
            eng.step()
        except Exception as e:    # the background loop's contract:
            errors.append(type(e).__name__)  # step already recovered
    for _ in range(800):          # post-storm drain, faults silent
        if eng.scheduler.idle():
            break
        try:
            eng.step()
        except Exception as e:
            errors.append(type(e).__name__)
    # -- invariants, asserted after EVERY storm -----------------------
    assert eng.scheduler.idle(), "engine failed to drain after storm"
    assert not eng._ring, "async ring holds futures at idle"
    outcomes = []
    for (pi, mn, sd, r) in reqs:
        assert r.done(), f"waiter never unblocked: {r}"
        if r.error is not None:
            outcomes.append((pi, mn, "err", type(r.error).__name__))
        else:
            out = r.result(timeout=0).tolist()
            if sd is None:        # greedy survivor: exact parity —
                #   cross-slot corruption would show up here
                assert out == refs[(pi, mn)], \
                    f"stream corruption: prompt {pi} max_new {mn}"
            outcomes.append((pi, mn, "ok", len(out)))
    assert eng.registry.get("serving.preemptions_total").value >= 1, \
        "storm never preempted (the scripted burst must)"
    eng.prefix_cache.clear()      # cache refs released ->
    assert eng.block_pool.in_use() == 0, "pool refcount leak"
    assert sum(1 for o in outcomes if o[2] == "ok") >= 1
    assert len(inj.log) >= 3, "storm fired too few faults to mean much"
    return inj.log, outcomes, errors


@pytest.mark.chaos
def test_chaos_storm_short_deterministic(tiny_gpt):
    """Tier-1 chaos: a ~60-tick seeded storm over the full-feature
    engine holds every recovery invariant, and the same seed
    reproduces the same fault schedule, error sequence, and
    per-request outcomes — while a different seed diverges."""
    prompts = _prompts()
    refs = {}
    # every GREEDY (prompt, max_new) pair the storm schedule submits
    for (pi, mn) in [(0, 12), (1, 10), (2, 12), (3, 6), (5, 10),
                     (0, 8), (1, 6), (2, 8)]:
        refs[(pi, mn)] = tiny_gpt.generate(
            paddle.to_tensor(prompts[pi][None, :]),
            max_new_tokens=mn).numpy()[0].tolist()
    a = _storm(tiny_gpt, seed=11, ticks=60, refs=refs)
    b = _storm(tiny_gpt, seed=11, ticks=60, refs=refs)
    c = _storm(tiny_gpt, seed=12, ticks=60, refs=refs)
    assert a[0] == b[0], "same seed, different fault schedule"
    assert a[1] == b[1], "same seed, different request outcomes"
    assert a[2] == b[2], "same seed, different error sequence"
    assert a[0] != c[0], "different seed, same fault schedule"


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_storm_long(tiny_gpt):
    """Longer storm (3 seeds x 150 ticks): every invariant, every
    seed."""
    prompts = _prompts()
    refs = {}
    for pi in range(len(prompts)):
        for mn in (6, 8, 10, 12):
            refs[(pi, mn)] = tiny_gpt.generate(
                paddle.to_tensor(prompts[pi][None, :]),
                max_new_tokens=mn).numpy()[0].tolist()
    for seed in (21, 22, 23):
        _storm(tiny_gpt, seed=seed, ticks=150, refs=refs)


# ---------------------------------------------------------------------------
# mid-migration chaos: kill the handoff at any of its three stages
# ---------------------------------------------------------------------------

def _await_demand(eng, demand, limit=200):
    """Step the engine until a wait=False migration demand resolves.
    Returns (verdict, None) or (None, error)."""
    for _ in range(limit):
        eng.step()
        try:
            return demand.wait(0), None
        except TimeoutError:
            continue
        except InjectedFault as e:
            return None, e
    raise AssertionError("migration demand never resolved")


def _migration_storm(model, seed, refs, ops=(0, 1, 2, 3, 4, 5, 0, 2)):
    """One seeded storm over a source/destination engine pair: every
    op starts a stream on the source, exports it mid-decode, and
    three independent seeded injectors may kill the handoff at any
    stage — export (source declines, stream keeps running there),
    wire (payload lost in flight, holder resumes from the emitted
    prefix), import (destination adopts nothing, the SAME payload
    retries at a later tick).  Asserts per op that EXACTLY ONE full
    stream comes out token-identical to the oracle, and at the end
    that both pools sit at refcount 0 — then returns the
    reproducibility signature (three fault logs + outcomes)."""
    inj_src = FaultInjector(seed=seed, rates={"migrate_export": 0.25})
    inj_dst = FaultInjector(seed=seed + 1,
                            rates={"migrate_import": 0.25})
    # the wire injector is driven HERE (the test is the transport),
    # with the op index as its tick — same purity contract
    wire = FaultInjector(seed=seed + 2, rates={"migrate_wire": 0.35})
    src = Engine(model, num_slots=2, max_seq_len=64, kv_block_size=8,
                 registry=monitor.StatRegistry(), faults=inj_src)
    dst = Engine(model, num_slots=2, max_seq_len=64, kv_block_size=8,
                 registry=monitor.StatRegistry(), faults=inj_dst)
    prompts = _prompts()
    MAX_NEW = 8
    outcomes = []
    for i, pi in enumerate(ops):
        p = prompts[pi]
        r = src.submit(p, max_new_tokens=MAX_NEW)
        for _ in range(400):
            if len(r.generated) >= 3 or r.done():
                break
            src.step()
        d = src.migrate_out(request_id=r.id, min_tokens=3,
                            deliver="return", wait=False)
        verdict, err = _await_demand(src, d)
        if err is not None:
            # export killed: declined, the stream NEVER left the
            # source — it decodes to completion right here
            for _ in range(400):
                if r.done():
                    break
                src.step()
            assert r.error is None, r.error
            assert r.result(timeout=0).tolist() == refs[pi]
            outcomes.append(("declined", pi))
            continue
        if verdict["completed"]:
            assert r.error is None
            assert r.result(timeout=0).tolist() == refs[pi]
            outcomes.append(("completed", pi))
            continue
        # the stream is terminal on the source; the payload is ours
        assert isinstance(r.error, Migrated)
        payload = verdict["payload"]
        emitted = [int(t) for t in verdict["generated"]]
        if wire.scheduled("migrate_wire", i):
            with pytest.raises(NetDisconnect):
                wire.fire("migrate_wire", i, emitted=emitted)
            # payload lost in flight — but the holder still has the
            # emitted prefix, so the stream RESUMES (greedy) on the
            # destination from prompt + emitted, never duplicated
            r2 = dst.submit(list(map(int, p)) + emitted,
                            max_new_tokens=MAX_NEW - len(emitted))
            for _ in range(400):
                if r2.done():
                    break
                dst.step()
            assert r2.error is None, r2.error
            assert r2.result(timeout=0).tolist() == refs[pi]
            outcomes.append(("wire_lost", pi, len(emitted)))
            continue
        adopted = None
        tries = 0
        for _ in range(4):
            tries += 1
            got, ierr = _await_demand(
                dst, dst.migrate_in(payload, wait=False))
            if got is not None:
                adopted = got
                break
            # import killed: the destination adopted NOTHING — the
            # identical payload is safe to replay at a later tick
        if adopted is None:
            r2 = dst.submit(list(map(int, p)) + emitted,
                            max_new_tokens=MAX_NEW - len(emitted))
            for _ in range(400):
                if r2.done():
                    break
                dst.step()
            assert r2.result(timeout=0).tolist() == refs[pi]
            outcomes.append(("import_gave_up", pi, tries))
            continue
        r2 = adopted["request"]
        for _ in range(400):
            if r2.done():
                break
            dst.step()
        assert r2.error is None, r2.error
        assert r2.result(timeout=0).tolist() == refs[pi]
        outcomes.append(("migrated", pi, adopted["blocks"], tries))
    # -- end invariants: both replicas drained, both pools at 0 -------
    for eng in (src, dst):
        for _ in range(400):
            if eng.scheduler.idle():
                break
            eng.step()
        assert eng.scheduler.idle()
        assert not eng._ring
        eng.prefix_cache.clear()
        assert eng.block_pool.in_use() == 0, \
            "mid-migration chaos leaked KV blocks"
    assert len(outcomes) == len(ops)  # exactly one verdict per stream
    return (tuple(inj_src.log), tuple(inj_dst.log), tuple(wire.log),
            tuple(outcomes))


@pytest.mark.chaos
@pytest.mark.migration
def test_migration_chaos_storm_deterministic(tiny_gpt):
    """Seeded mid-migration kill storm: under injected deaths at
    export, wire, and import, every stream completes EXACTLY once
    token-identical to its oracle, both pools end at refcount 0, and
    the same seed replays the same fault/migration log while a
    different seed diverges."""
    prompts = _prompts()
    refs = {pi: tiny_gpt.generate(
        paddle.to_tensor(prompts[pi][None, :]),
        max_new_tokens=8).numpy()[0].tolist()
        for pi in range(len(prompts))}
    a = _migration_storm(tiny_gpt, seed=5, refs=refs)
    b = _migration_storm(tiny_gpt, seed=5, refs=refs)
    c = _migration_storm(tiny_gpt, seed=6, refs=refs)
    assert a == b, "same seed, different fault/migration history"
    assert a != c, "different seed, same fault/migration history"
    # the two seeds together must exercise every migration stage, or
    # the storm proves nothing
    fired = {site for sig in (a, c) for log in sig[:3]
             for (_, site) in log}
    assert fired == {"migrate_export", "migrate_wire",
                     "migrate_import"}, fired
    kinds = {o[0] for sig in (a, c) for o in sig[3]}
    assert "migrated" in kinds and "declined" in kinds, kinds


# ---------------------------------------------------------------------------
# front-end chaos: adapter hot-swap + streaming client kills mid-traffic
# ---------------------------------------------------------------------------

def _pump(stream, it):
    """Consume every event a stream has buffered RIGHT NOW without
    blocking: heartbeat_s=0 turns an empty queue into an immediate
    heartbeat, which is the 'caught up' signal.  A scheduled client
    kill surfaces as StreamDisconnect out of the iterator — this
    consumer just dies quietly, like the real one would."""
    while not stream.closed:
        try:
            if next(it).kind == "heartbeat":
                break
        except StreamDisconnect:
            return


def _frontend_storm(model, seed, ticks, refs):
    """One seeded storm over a LoRA-serving engine with live streaming
    clients.  Mid-traffic the driver hot-loads/unloads adapter lanes —
    the injected ``adapter_load`` site kills some swaps at the bank
    write (inventory must stay untouched) and pinned unloads must be
    REFUSED, not deferred — while seeded ``stream_disconnect`` clients
    vanish mid-response (the engine must not care).  Asserts the
    invariant set and returns the reproducibility signature."""
    n_layers = len(list(model.blocks))
    hidden = int(model.embeddings.word_embeddings.weight.shape[1])
    a1 = LoRAAdapter.random(4, hidden, n_layers=n_layers, seed=11,
                            scale=0.5)
    a2 = LoRAAdapter.random(2, hidden, n_layers=n_layers, seed=22,
                            scale=0.5)
    inj = FaultInjector(seed=seed,
                        rates={"adapter_load": 0.45, "dispatch": 0.02},
                        first_tick=0, last_tick=ticks)
    # the CLIENT-side injector: its "tick" is the stream ordinal, so
    # which clients vanish is pure (seed, ordinal) — independent of
    # engine timing
    cinj = FaultInjector(seed=seed + 7,
                         rates={"stream_disconnect": 0.5},
                         first_tick=0, last_tick=10 ** 9)
    eng = Engine(model, num_slots=3, max_seq_len=64, kv_block_size=8,
                 adapters={"a1": a1}, max_adapters=4,
                 registry=monitor.StatRegistry())
    prompts = _prompts()
    for i in range(2):                  # warm compiles, faults unarmed
        eng.submit(prompts[i], max_new_tokens=2)
    eng.run_until_idle()
    inj.first_tick += eng.tick_no
    inj.last_tick += eng.tick_no
    eng.faults = inj
    # (tick, prompt_idx, max_new, adapter) — adapter "a2?" means "a2
    # if its hot-load has landed by then, else base"
    sched = {
        0: [(0, 10, None), (1, 8, "a1")],
        3: [(2, 8, "a1")],
        7: [(3, 8, "a2?")],
        12: [(0, 6, None), (4, 8, "a2?")],
        18: [(1, 8, "a1"), (2, 6, "a2?")],
    }
    swap_log, reqs, streams = [], [], []
    a2_loaded = False
    for t in range(ticks):
        if t >= 2 and not a2_loaded:    # hot-load a2, retrying past
            if t == 2:                  # the FIRST attempt is always
                inj.at(eng.tick_no, "adapter_load")  # killed mid-swap
            try:                        # injected adapter_load kills
                eng.load_adapter("a2", a2)
                a2_loaded = True
                swap_log.append(("load", "a2", "ok"))
            except InjectedFault:
                swap_log.append(("load", "a2", "fault"))
                assert eng.adapters.names() == ["a1"], \
                    "failed load mutated the inventory"
        if (("unload", "a1", "refused") not in swap_log
                and eng.adapters.pins("a1") > 0):
            try:                        # a1 pinned by live streams:
                eng.unload_adapter("a1")  # must REFUSE, not wait
                swap_log.append(("unload", "a1", "ok"))
            except AdapterInUse:
                swap_log.append(("unload", "a1", "refused"))
            except InjectedFault:
                swap_log.append(("unload", "a1", "fault"))
        for (pi, mn, ad) in sched.get(t, []):
            if ad == "a2?":
                ad = "a2" if a2_loaded else None
            r = eng.submit(prompts[pi], max_new_tokens=mn, adapter=ad)
            s = TokenStream(r, heartbeat_s=0.0, faults=cinj,
                            ordinal=len(streams))
            reqs.append((pi, mn, ad, r))
            streams.append((s, iter(s)))
        try:
            eng.step()
        except Exception:  # noqa: BLE001 — step already recovered
            pass
        for (s, it) in streams:         # live clients keep up; the
            _pump(s, it)                # scheduled ones vanish here
    for _ in range(600):
        if eng.scheduler.idle():
            break
        try:
            eng.step()
        except Exception:  # noqa: BLE001
            pass
    # -- invariants ---------------------------------------------------
    assert eng.scheduler.idle(), "engine failed to drain after storm"
    for name in eng.adapters.names():
        assert eng.adapters.pins(name) == 0, f"{name}: leaked pin"
    assert eng.streams_active() == 0, "request sinks leaked"
    outcomes = []
    for (snum, ((pi, mn, ad, r), (s, it))) in enumerate(
            zip(reqs, streams)):
        assert r.done(), f"waiter never unblocked: {r}"
        if r.error is not None:
            outcomes.append((pi, mn, ad, "err", type(r.error).__name__))
            continue
        out = [int(x) for x in r.generated]
        assert out == refs[(pi, mn, ad)], \
            f"stream corruption: prompt {pi} adapter {ad}"
        if s._disconnect_after is not None:
            # killed client: what it DID deliver is an exact dup-free
            # prefix — never a scrambled or doubled suffix
            assert s.closed and s.tokens == out[:len(s.tokens)], snum
            assert 1 <= len(s.tokens) < len(out), snum
            outcomes.append((pi, mn, ad, "cut", len(s.tokens)))
        else:
            _pump(s, it)                # consume the terminal event
            assert s.tokens == out, f"stream {snum}: delivery != land"
            outcomes.append((pi, mn, ad, "ok", len(s.tokens)))
    eng.prefix_cache.clear()
    assert eng.block_pool.in_use() == 0, "pool refcount leak"
    cut = [o for o in outcomes if o[3] == "cut"]
    ok = [o for o in outcomes if o[3] == "ok"]
    assert cut and ok, (cut, ok)
    assert ("unload", "a1", "refused") in swap_log, swap_log
    assert a2_loaded and "a2" in eng.adapters.names()
    return (tuple(inj.log), tuple(cinj.log), tuple(swap_log),
            tuple(outcomes))


@pytest.mark.chaos
@pytest.mark.lora
@pytest.mark.stream
def test_frontend_chaos_storm_deterministic(tiny_gpt):
    """Seeded LoRA + streaming storm: adapter hot-swaps under injected
    bank-write kills, pinned unload refusal, and mid-response client
    disconnects — every surviving request lands token-identical to its
    merged-weights oracle with zero leaked pins/sinks, every client
    delivery is exactly-once (full or clean prefix), and the same seed
    replays the same fault/swap/outcome history."""
    prompts = _prompts()
    n_layers = len(list(tiny_gpt.blocks))
    hidden = int(tiny_gpt.embeddings.word_embeddings.weight.shape[1])
    oracles = {None: tiny_gpt}
    for name, lseed, rank in (("a1", 11, 4), ("a2", 22, 2)):
        ad = LoRAAdapter.random(rank, hidden, n_layers=n_layers,
                                seed=lseed, scale=0.5)
        paddle.seed(0)
        m = GPTModel.from_config("tiny", dropout=0.0)
        m.eval()
        oracles[name] = ad.merge_into(m)
    refs = {}
    for (pi, mn) in {(0, 10), (1, 8), (2, 8), (3, 8), (0, 6), (4, 8),
                     (2, 6)}:
        for ad in (None, "a1", "a2"):
            # generate() returns prompt + continuation; the storm
            # compares Request.generated (the continuation alone)
            refs[(pi, mn, ad)] = oracles[ad].generate(
                paddle.to_tensor(prompts[pi][None, :]),
                max_new_tokens=mn).numpy()[0][len(prompts[pi]):].tolist()
    a = _frontend_storm(tiny_gpt, seed=31, ticks=26, refs=refs)
    b = _frontend_storm(tiny_gpt, seed=31, ticks=26, refs=refs)
    c = _frontend_storm(tiny_gpt, seed=33, ticks=26, refs=refs)
    assert a == b, "same seed, different storm history"
    assert a != c, "different seed, same storm history"
    fired = {site for sig in (a, c) for log in sig[:2]
             for (_, site) in log}
    assert "adapter_load" in fired and "stream_disconnect" in fired


# ---------------------------------------------------------------------------
# offload chaos: kill the host tier's demote/promote mid-traffic
# ---------------------------------------------------------------------------

def _shared_prompts(base_len=24, suffix_lens=(4, 10, 18, 12),
                    rng_seed=9):
    """Prompts sharing a ``base_len``-token prefix (3 full blocks at
    block_size 8) with distinct VARIED-length suffixes — the shape that
    makes the host tier earn its keep: evicting the shared span demotes
    ONE set of content-addressed entries every later prompt can
    promote, while the longer suffixes add private full blocks so the
    store holds entries of several depths."""
    rng = np.random.RandomState(rng_seed)
    base = rng.randint(0, 128, (base_len,)).astype(np.int32).tolist()
    return [np.array(base + rng.randint(0, 128, (sl,)).tolist(),
                     dtype=np.int32) for sl in suffix_lens]


def _offload_storm(model, seed, ticks, refs, prompts):
    """One seeded storm over a host-tier engine under a TIGHT device
    pool: scripted spills (prefix-cache evict + flush) and shared-prefix
    resubmissions force demote->promote cycles while the injected
    ``offload_demote`` site makes evicted blocks free WITHOUT spilling
    and ``offload_promote`` makes admissions fall back to recompute —
    mixed with a scripted priority burst (preemption) and a mid-storm
    ``migrate_out`` handoff to a second replica.  Asserts the invariant
    set (every waiter unblocked, greedy survivors token-identical, BOTH
    tiers at zero after clear, host byte accounting exact) and returns
    the reproducibility signature."""
    inj = FaultInjector(
        seed=seed,
        rates={"offload_demote": 0.35, "offload_promote": 0.5,
               "dispatch": 0.03},
        # first ticks fault-free so the scripted burst preempts
        # deterministically; nothing fires past the window so the
        # post-storm round-trip below sees a live tier
        first_tick=4, last_tick=ticks)
    eng = Engine(model, num_slots=2, max_seq_len=64, kv_block_size=8,
                 kv_blocks=16, prefill_chunk=8, tick_token_budget=16,
                 kv_host_mb=64, registry=monitor.StatRegistry())
    dst = Engine(model, num_slots=2, max_seq_len=64, kv_block_size=8,
                 registry=monitor.StatRegistry())
    st = eng.host_store
    for i in range(2):                 # warm compiles, faults unarmed
        eng.submit(prompts[i], max_new_tokens=2)
    eng.run_until_idle()
    inj.first_tick += eng.tick_no
    inj.last_tick += eng.tick_no
    eng.faults = inj
    # (prompt_idx, max_new, priority, sample_seed); the t=2 burst lands
    # while both slots hold pri-0 streams -> preemption; resubmission
    # waves after each spill drive the promote path under fire
    sched = {
        0: [(0, 10, 0, None), (1, 8, 0, None)],
        2: [(2, 6, 5, None)],
        6: [(3, 12, 0, None)],
        10: [(0, 6, 0, None), (1, 6, 0, 42)],
        14: [(2, 8, 0, None)],
        18: [(0, 8, 0, None)],
        22: [(1, 10, 0, None)],
        26: [(3, 6, 0, None)],
        30: [(0, 10, 0, None)],
    }
    # scripted eviction pressure: each spill is one demote consult
    # tick, so spreading them over many ticks gives the injected
    # offload_demote schedule real surface to hit
    spill_at = (4, 8, 12, 16, 20, 24, 28, 32)
    reqs, errors = [], []
    r_mig, mig_demand = None, None
    for t in range(ticks):
        for (pi, mn, pri, sd) in sched.get(t, []):
            kw = ({} if sd is None else
                  {"temperature": 0.9, "top_p": 0.9, "seed": sd})
            r = eng.submit(prompts[pi], max_new_tokens=mn,
                           priority=pri, **kw)
            if t == 6:                 # the migration candidate
                r_mig = r
            else:
                reqs.append((pi, mn, sd, r))
        if t in spill_at:
            eng.prefix_cache.evict(10 ** 6)
            eng._flush_offload()
        if (t >= 12 and mig_demand is None and r_mig is not None
                and not r_mig.done() and len(r_mig.generated) >= 3):
            mig_demand = eng.migrate_out(request_id=r_mig.id,
                                         min_tokens=3,
                                         deliver="return", wait=False)
        try:
            eng.step()
        except Exception as e:        # step already recovered
            errors.append(type(e).__name__)
    for _ in range(800):              # post-storm drain, faults silent
        if eng.scheduler.idle():
            break
        try:
            eng.step()
        except Exception as e:
            errors.append(type(e).__name__)
    # -- migration handoff resolves to exactly one full stream --------
    if mig_demand is None:
        mig_outcome = ("skipped",)
        if r_mig is not None:
            reqs.append((3, 12, None, r_mig))
    else:
        verdict, err = _await_demand(eng, mig_demand)
        assert err is None, err       # no migrate sites in the rates
        if verdict["completed"]:
            assert r_mig.error is None
            assert r_mig.result(timeout=0).tolist() == refs[(3, 12)]
            mig_outcome = ("completed",)
        else:
            assert isinstance(r_mig.error, Migrated)
            got, ierr = _await_demand(
                dst, dst.migrate_in(verdict["payload"], wait=False))
            assert ierr is None and got is not None
            r2 = got["request"]
            for _ in range(400):
                if r2.done():
                    break
                dst.step()
            assert r2.error is None, r2.error
            assert r2.result(timeout=0).tolist() == refs[(3, 12)]
            mig_outcome = ("migrated", int(got["blocks"]))
    # -- invariants, asserted after EVERY storm -----------------------
    assert eng.scheduler.idle(), "engine failed to drain after storm"
    assert not eng._ring, "async ring holds futures at idle"
    outcomes = []
    for (pi, mn, sd, r) in reqs:
        assert r.done(), f"waiter never unblocked: {r}"
        if r.error is not None:
            outcomes.append((pi, mn, "err", type(r.error).__name__))
        else:
            out = r.result(timeout=0).tolist()
            if sd is None:            # greedy survivor: exact parity —
                # a corrupted demote/promote payload shows up here
                assert out == refs[(pi, mn)], \
                    f"stream corruption: prompt {pi} max_new {mn}"
            outcomes.append((pi, mn, "ok", len(out)))
    assert eng.registry.get("serving.preemptions_total").value >= 1, \
        "storm never preempted (the scripted burst must)"
    assert len(inj.log) >= 3, "storm fired too few faults to mean much"
    # -- past the window every stage is live again: one clean
    #    demote->promote round-trip proves neither tier was corrupted
    pre_p = int(eng._m_offload_promotes.value)
    r0 = eng.submit(prompts[0], max_new_tokens=6)
    eng.run_until_idle()
    assert r0.result(timeout=0).tolist() == refs[(0, 6)]
    eng.prefix_cache.evict(10 ** 6)
    eng._flush_offload()
    assert len(st) >= 3, "clean spill parked nothing"
    r1 = eng.submit(prompts[0], max_new_tokens=6)
    eng.run_until_idle()
    assert r1.result(timeout=0).tolist() == refs[(0, 6)]
    assert int(eng._m_offload_promotes.value) >= pre_p + 3, \
        "clean promote did not restore the spilled prefix"
    # -- host byte accounting EXACT: every resident entry is one fp32
    #    block; nothing else may count
    entry_bytes = int(np.prod(st._want)) * 4
    assert st.bytes_used == len(st) * entry_bytes, \
        (st.bytes_used, len(st), entry_bytes)
    assert st.stats()["bytes"] == st.bytes_used
    host_sig = (len(st), st.bytes_used,
                int(eng._m_offload_demotes.value),
                int(eng._m_offload_promotes.value),
                int(eng._m_offload_hit_tokens.value),
                st.hits, st.misses, st.dedup_puts)
    # -- refcounts -> 0 in BOTH tiers
    for e in (eng, dst):
        for _ in range(400):
            if e.scheduler.idle():
                break
            e.step()
        e.prefix_cache.clear()
        assert e.block_pool.in_use() == 0, "offload storm leaked blocks"
    st.clear()
    assert len(st) == 0 and st.bytes_used == 0, \
        "host tier accounting survived clear()"
    return (tuple(inj.log), tuple(outcomes), tuple(errors),
            mig_outcome, host_sig)


@pytest.mark.chaos
@pytest.mark.offload
def test_offload_chaos_storm_deterministic(tiny_gpt):
    """Seeded host-tier storm: under injected demote kills (block frees
    without spilling) and promote kills (admission recomputes), mixed
    with preemption and a mid-storm migration handoff, every greedy
    survivor stays token-identical, host byte accounting stays exact,
    both tiers drain to zero — and the same seed replays the same
    fault/outcome/error history while a different seed diverges."""
    prompts = _shared_prompts()
    refs = {}
    for (pi, mn) in [(0, 10), (1, 8), (2, 6), (3, 12), (0, 6),
                     (2, 8), (0, 8), (1, 10), (3, 6)]:
        refs[(pi, mn)] = tiny_gpt.generate(
            paddle.to_tensor(prompts[pi][None, :]),
            max_new_tokens=mn).numpy()[0].tolist()
    a = _offload_storm(tiny_gpt, seed=41, ticks=40, refs=refs,
                       prompts=prompts)
    b = _offload_storm(tiny_gpt, seed=41, ticks=40, refs=refs,
                       prompts=prompts)
    c = _offload_storm(tiny_gpt, seed=43, ticks=40, refs=refs,
                       prompts=prompts)
    assert a == b, "same seed, different storm history"
    assert a != c, "different seed, same storm history"
    # across the two seeds BOTH offload sites must actually fire, or
    # the storm proves nothing about the tier under failure
    fired = {site for sig in (a, c) for (_, site) in sig[0]}
    assert {"offload_demote", "offload_promote"} <= fired, fired
