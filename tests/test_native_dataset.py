"""Native dataset engine: file ingestion, shuffle, sharding,
train_from_dataset.

Mirrors reference tests fluid/tests/unittests/test_dataset.py (filelist →
load_into_memory → local/global shuffle → train_from_dataset).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.io import InMemoryDataset, QueueDataset, DatasetFactory
from paddle_tpu import csrc


class _Var:
    def __init__(self, name, shape, dtype="float32"):
        self.name = name
        self.shape = shape
        self.dtype = dtype


@pytest.fixture()
def data_files(tmp_path):
    rng = np.random.RandomState(0)
    files = []
    for i in range(3):
        path = tmp_path / f"part-{i}.txt"
        rows = []
        for _ in range(40):
            x = rng.rand(4)
            label = float(x.sum() > 2.0)
            rows.append(" ".join(f"{v:.6f}" for v in x) + f" {label}")
        path.write_text("\n".join(rows) + "\n")
        files.append(str(path))
    return files


def test_native_engine_available():
    assert csrc.available(), "libptq.so should build in this environment"


def test_load_shuffle_iterate(data_files):
    ds = InMemoryDataset()
    ds.set_filelist(data_files)
    ds.set_use_var([_Var("x", [-1, 4]), _Var("y", [-1, 1])])
    ds.set_batch_size(16)
    n = ds.load_into_memory()
    assert n == 120
    assert ds.get_memory_data_size() == 120
    first = next(iter(ds))
    assert first[0].shape == (16, 4)
    assert first[1].shape == (16, 1)
    before = first[0].copy()
    ds.local_shuffle()
    after = next(iter(ds))[0]
    assert not np.array_equal(before, after)
    # all records still present across one epoch
    total = sum(len(b[0]) for b in ds)
    assert total == 112  # 120 - remainder(8) with bs 16


def test_global_shuffle_shards_disjoint(data_files, monkeypatch):
    from paddle_tpu.distributed import parallel as dp
    sets = []
    for rank in range(2):
        ds = InMemoryDataset()
        ds.set_filelist(data_files)
        ds.set_use_var([_Var("x", [-1, 4]), _Var("y", [-1, 1])])
        ds.set_batch_size(10)
        ds.load_into_memory()
        monkeypatch.setattr(dp, "get_rank", lambda group=None, r=rank: r)
        monkeypatch.setattr(dp, "get_world_size", lambda group=None: 2)
        ds.global_shuffle()
        assert ds.get_shuffle_data_size() == 60
        rows = np.concatenate([b[0] for b in ds])
        sets.append({tuple(np.round(r, 5)) for r in rows})
    assert not (sets[0] & sets[1])  # disjoint shards


def test_queue_dataset_no_shuffle(data_files):
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_filelist(data_files)
    ds.set_use_var([_Var("x", [-1, 4]), _Var("y", [-1, 1])])
    ds.set_batch_size(8)
    with pytest.raises(RuntimeError):
        ds.local_shuffle()
    batches = list(ds)
    assert len(batches) == 15


def test_train_from_dataset(data_files):
    paddle.enable_static()
    main = static.Program()
    try:
        with static.program_guard(main):
            x = static.data("x", [16, 4])
            y = static.data("y", [16, 1])
            pred = static.nn.fc(x, 1)
            loss = paddle.mean((pred - y) ** 2)
            from paddle_tpu import optimizer
            optimizer.SGD(learning_rate=0.1).minimize(loss)

            ds = InMemoryDataset()
            ds.set_filelist(data_files)
            ds.set_use_var([x, y])
            ds.set_batch_size(16)
            ds.load_into_memory()
            ds.local_shuffle()

            exe = static.Executor()
            losses = []
            for _ in range(5):  # epochs
                out = exe.train_from_dataset(main, ds, fetch_list=[loss])
                losses.append(float(np.mean([o[0] for o in out])))
        assert losses[-1] < losses[0]
    finally:
        paddle.disable_static()


def test_release_memory(data_files):
    ds = InMemoryDataset()
    ds.set_filelist(data_files)
    ds.set_use_var([_Var("x", [-1, 4]), _Var("y", [-1, 1])])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 120
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


# ---- regressions from code review ----------------------------------------

def test_record_order_deterministic_across_threads(data_files):
    orders = []
    for threads in (1, 4):
        ds = InMemoryDataset()
        ds.set_filelist(data_files)
        ds.set_use_var([_Var("x", [-1, 4]), _Var("y", [-1, 1])])
        ds.set_thread(threads)
        ds.set_batch_size(120)
        ds.load_into_memory()
        orders.append(next(iter(ds))[0])
    np.testing.assert_array_equal(orders[0], orders[1])


def test_long_lines_single_record(tmp_path):
    # a >64KiB line must stay ONE (truncated) record, not split into many
    path = tmp_path / "wide.txt"
    vals = " ".join("1.5" for _ in range(20000))  # ~100KB line
    path.write_text(vals + "\n" + "2.0 2.0 2.0 2.0\n")
    ds = InMemoryDataset()
    ds.set_filelist([str(path)])
    ds.set_use_var([_Var("x", [-1, 4])])
    n = ds.load_into_memory()
    assert n == 2
    ds.set_batch_size(2)
    batch = next(iter(ds))[0]
    np.testing.assert_allclose(batch[0], [1.5] * 4)
    np.testing.assert_allclose(batch[1], [2.0] * 4)


def test_global_shuffle_idempotent_per_epoch(data_files, monkeypatch):
    from paddle_tpu.distributed import parallel as dp
    ds = InMemoryDataset()
    ds.set_filelist(data_files)
    ds.set_use_var([_Var("x", [-1, 4]), _Var("y", [-1, 1])])
    ds.load_into_memory()
    monkeypatch.setattr(dp, "get_rank", lambda group=None: 0)
    monkeypatch.setattr(dp, "get_world_size", lambda group=None: 2)
    ds.global_shuffle()
    assert ds.get_shuffle_data_size() == 60
    first_epoch = {tuple(np.round(r, 5))
                   for b in ds for r in b[0]}
    ds.global_shuffle()  # second epoch: re-derives, does NOT shrink
    assert ds.get_shuffle_data_size() == 60
    second_epoch = {tuple(np.round(r, 5))
                    for b in ds for r in b[0]}
    assert first_epoch != second_epoch  # fresh partition per epoch
