"""Three-way execution-mode equivalence: eager (dygraph) vs @to_static
(jit) vs the static-graph Program must agree numerically.

Reference parity: the dygraph_to_static test suite (SURVEY §4 —
"run the same nn.Layer eagerly and via @to_static, asserting numerical
equality — doubles as autodiff regression"), extended with the recorded
Program as a third mode."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static
import paddle_tpu.nn.functional as F


def _mlp():
    return nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))


def _cnn_bn():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.conv = nn.Conv2D(2, 4, 3, padding=1)
            self.bn = nn.BatchNorm2D(4)
            self.fc = nn.Linear(4 * 4 * 4, 3)

        def forward(self, x):
            h = F.relu(self.bn(self.conv(x)))
            h = paddle.reshape(h, [h.shape[0], -1])
            return self.fc(h)
    return Net()


def _transformer_block():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.enc = nn.TransformerEncoderLayer(
                d_model=16, nhead=4, dim_feedforward=32, dropout=0.0)
            self.out = nn.Linear(16, 2)

        def forward(self, x):
            return self.out(paddle.mean(self.enc(x), axis=1))
    return Net()


CASES = [
    ("mlp", _mlp, (5, 8)),
    ("cnn_bn", _cnn_bn, (5, 2, 4, 4)),
    ("transformer", _transformer_block, (3, 7, 16)),
]


@pytest.mark.parametrize("name,builder,in_shape", CASES,
                         ids=[c[0] for c in CASES])
def test_eager_vs_to_static_forward(name, builder, in_shape):
    paddle.seed(0)
    net = builder()
    net.eval()
    x = np.random.RandomState(1).rand(*in_shape).astype("float32")
    eager = net(paddle.to_tensor(x)).numpy()
    jitted = paddle.jit.to_static(net)
    compiled = jitted(paddle.to_tensor(x))
    np.testing.assert_allclose(compiled.numpy(), eager, rtol=2e-4,
                               atol=2e-5)


@pytest.mark.parametrize("name,builder,in_shape", CASES,
                         ids=[c[0] for c in CASES])
def test_eager_vs_static_program_forward(name, builder, in_shape):
    paddle.seed(0)
    net = builder()
    net.eval()
    x = np.random.RandomState(2).rand(*in_shape).astype("float32")
    eager = net(paddle.to_tensor(x)).numpy()
    main = static.Program()
    paddle.enable_static()
    try:
        with static.program_guard(main):
            xin = static.data("x", list(in_shape))
            out = net(xin)    # same Layer records into the Program
            exe = static.Executor()
            got, = exe.run(main, feed={"x": x}, fetch_list=[out])
    finally:
        paddle.disable_static()
    np.testing.assert_allclose(got, eager, rtol=2e-4, atol=2e-5)


def test_training_equivalence_eager_vs_static():
    # identical init + identical data -> identical loss trajectories
    x = np.random.RandomState(3).rand(16, 8).astype("float32")
    y = np.random.RandomState(4).rand(16, 1).astype("float32")

    paddle.seed(7)
    net_e = nn.Linear(8, 1)
    from paddle_tpu import optimizer
    opt_e = optimizer.SGD(learning_rate=0.1,
                          parameters=net_e.parameters())
    eager_losses = []
    for _ in range(10):
        loss = paddle.mean((net_e(paddle.to_tensor(x))
                            - paddle.to_tensor(y)) ** 2)
        loss.backward()
        opt_e.step()
        opt_e.clear_grad()
        eager_losses.append(float(loss.numpy()))

    paddle.seed(7)
    net_s = nn.Linear(8, 1)
    main = static.Program()
    paddle.enable_static()
    try:
        with static.program_guard(main):
            xin = static.data("x", [16, 8])
            yin = static.data("y", [16, 1])
            loss = paddle.mean((net_s(xin) - yin) ** 2)
            optimizer.SGD(learning_rate=0.1,
                          parameters=net_s.parameters()).minimize(loss)
            exe = static.Executor()
            static_losses = [
                float(exe.run(main, feed={"x": x, "y": y},
                              fetch_list=[loss])[0])
                for _ in range(10)]
    finally:
        paddle.disable_static()
    np.testing.assert_allclose(static_losses, eager_losses, rtol=1e-4)


def test_grad_equivalence_eager_vs_jax_grad():
    # the eager tape must agree with jax.grad over the same function
    import jax
    import jax.numpy as jnp
    paddle.seed(5)
    net = _mlp()
    x = np.random.RandomState(6).rand(4, 8).astype("float32")

    t = paddle.to_tensor(x, stop_gradient=False)
    out = paddle.sum(net(t) ** 2)
    out.backward()
    tape_grad = t.grad.numpy()

    from paddle_tpu.jit import functional_call
    params = {k: v._data for k, v in net.named_parameters()}

    def f(xa):
        out, _ = functional_call(net, params, {}, [xa], training=False)
        return (out ** 2).sum()

    jax_grad = jax.grad(f)(jnp.asarray(x))
    np.testing.assert_allclose(tape_grad, np.asarray(jax_grad),
                               rtol=1e-4, atol=1e-5)


def test_to_static_value_branch_gives_helpful_error():
    class Branchy(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 1)

        def forward(self, x):
            h = self.fc(x)
            if h.sum() > 0:          # value-dependent Python branch
                return h * 2
            return h

    sf = paddle.jit.to_static(Branchy())
    with pytest.raises(TypeError, match="cond"):
        sf(paddle.to_tensor(np.ones((2, 4), "float32")))
