"""Chrome-trace timeline assembler (reference: tools/timeline.py).

The reference converter turned profiler protobufs into a
chrome://tracing JSON; here the serving tracer already speaks Catapult
natively, so this tool's job is COLLECTION: fetch traces from live
engines (``GET /debug/trace``), load flight-recorder dumps or
``stop_profiler(profile_path=...)`` files, normalize bare event lists,
and merge any number of them into ONE timeline — each source gets its
own ``pid`` lane so a multi-engine (or engine + profiler) view lines
up side by side in chrome://tracing / Perfetto.

Usage:
    python tools/timeline.py trace1.json http://host:port/debug/trace \
        [--out timeline.json]
    python tools/timeline.py --router http://routerhost:port \
        [--out timeline.json]

``--router`` expands a routerd base URL into the router's own
``/debug/trace`` PLUS every replica's, by asking its ``/replicas``
registry — the whole fleet lands in one timeline, the router's
route.pick/route.retry/route.hedge/probe spans on pid 0 and each
replica's ticks on its own pid, labeled ``replica:<name>``.

With no ``--out`` the merged trace goes to stdout.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def load_trace(source, timeout=10.0):
    """Load one trace: an ``http(s)://`` URL (a live engine's
    ``/debug/trace``) or a file path.  Accepts the Catapult object
    form ({"traceEvents": [...]}) or a bare event list; returns the
    object form."""
    if str(source).startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=timeout) as resp:
            data = json.loads(resp.read())
    else:
        with open(source) as f:
            data = json.load(f)
    if isinstance(data, list):  # bare event list -> object form
        data = {"traceEvents": data}
    if "traceEvents" not in data or not isinstance(
            data["traceEvents"], list):
        raise ValueError(
            f"{source}: not a chrome trace (no traceEvents array)")
    return data


def router_sources(base_url, timeout=10.0):
    """Expand a routerd base URL into (label, trace_source) pairs:
    the router's own /debug/trace first, then one per replica from
    its /replicas registry (replicas whose address the router cannot
    name — e.g. in-process test replicas — are skipped with a note
    on stderr; there is no URL to fetch)."""
    base = base_url.rstrip("/")
    with urllib.request.urlopen(base + "/replicas",
                                timeout=timeout) as resp:
        table = json.loads(resp.read())
    out = [("router", base + "/debug/trace")]
    for row in table.get("replicas", []):
        addr = row.get("address")
        name = row.get("name", "?")
        # mesh-sharded replicas get labeled with their full (mp, dp)
        # mesh degrees (the registry carries the probed mesh signals)
        # — a fleet timeline distinguishes a 4-chip "mp=2 dp=2"
        # replica's lane from a single-chip one's at a glance; dp=1
        # is omitted so unsharded and pure-mp labels stay stable
        sig = row.get("signals") or {}
        mp, dp = sig.get("mp"), sig.get("dp")
        label = (f"replica:{name} mp={int(mp)}"
                 if mp and int(mp) > 1 else f"replica:{name}")
        if dp and int(dp) > 1:
            if not (mp and int(mp) > 1):
                label += f" mp={int(mp or 1)}"
            label += f" dp={int(dp)}"
        # supervised replicas carry their restart generation — a
        # respawned replica's lane is visibly a NEW incarnation, not
        # a continuation of the dead one's
        inc = row.get("incarnation")
        if inc is not None and int(inc) > 0:
            label += f" inc={int(inc)}"
        # multi-LoRA replicas carry their probed adapter inventory —
        # the fleet timeline shows at a glance which lanes can serve
        # a given model= request
        adapters = (row.get("signals") or {}).get("adapters")
        if adapters:
            label += " adapters=" + ",".join(
                str(a) for a in adapters)
        if not addr or not str(addr).startswith(("http://",
                                                 "https://")):
            print(f"replica {name}: no fetchable address "
                  f"({addr!r}) — skipped", file=sys.stderr)
            continue
        out.append((label,
                    str(addr).rstrip("/") + "/debug/trace"))
    return out


def merge_traces(traces, labels=None):
    """Merge trace objects into one timeline.  Each input is assigned
    its own ``pid`` (0, 1, ...) — sources may come from different
    processes whose original pids could collide — and gets a
    ``process_name`` metadata row from ``labels``.  Non-event keys of
    the FIRST trace carrying them (``metadata`` — e.g. a flight
    recorder's error context) are preserved."""
    out = {"traceEvents": [], "displayTimeUnit": "ms"}
    for pid, trace in enumerate(traces):
        label = (labels[pid] if labels and pid < len(labels)
                 else f"trace{pid}")
        seen_pname = False
        for ev in trace["traceEvents"]:
            ev = dict(ev)
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                seen_pname = True
            ev["pid"] = pid
            out["traceEvents"].append(ev)
        if not seen_pname:
            out["traceEvents"].insert(
                len(out["traceEvents"]) - len(trace["traceEvents"]),
                {"name": "process_name", "ph": "M", "pid": pid,
                 "tid": 0, "args": {"name": label}})
        for k, v in trace.items():
            if k not in ("traceEvents", "displayTimeUnit") \
                    and k not in out:
                out[k] = v
    return out


def lifecycle_counts(trace):
    """Instant-event counts by name for one trace — the request
    lifecycle view (req.queued/admitted/first_token/finished/evicted
    and the overload instants req.preempted / req.resumed /
    req.shed[reason], fault.injected, engine.watchdog).
    (trace_view.py's ``lifecycle_summary`` is the sorted-rows twin —
    both tools stay single-file standalone by design, so a key-format
    change must be mirrored there.)"""
    counts = {}
    for ev in trace["traceEvents"]:
        if ev.get("ph") != "i":
            continue
        reason = (ev.get("args") or {}).get("reason")
        key = (f"{ev.get('name', '?')}[{reason}]" if reason
               else ev.get("name", "?"))
        counts[key] = counts.get(key, 0) + 1
    return counts


def main(argv=None):
    p = argparse.ArgumentParser(
        description="merge serving traces / flight-recorder dumps / "
                    "live /debug/trace endpoints into one "
                    "chrome://tracing timeline")
    p.add_argument("sources", nargs="*",
                   help="trace file paths and/or /debug/trace URLs")
    p.add_argument("--router", default=None, metavar="URL",
                   help="routerd base URL: merge the router's trace "
                        "with every replica's /debug/trace (from its "
                        "/replicas registry), one pid per replica")
    p.add_argument("--out", default=None,
                   help="output path (default: stdout)")
    p.add_argument("--lifecycle", action="store_true",
                   help="print per-source request-lifecycle instant "
                        "counts (incl. req.preempted/resumed/shed) "
                        "to stderr alongside the merge")
    args = p.parse_args(argv)
    pairs = [(str(s), s) for s in args.sources]
    n_positional = len(pairs)
    if args.router:
        pairs += router_sources(args.router)
    if not pairs:
        p.error("no sources: give trace files/URLs and/or --router")
    labels, traces = [], []
    for i, (lbl, src) in enumerate(pairs):
        try:
            tr = load_trace(src)
        except Exception as e:
            if i < n_positional:
                raise         # an explicit source must exist
            # a fleet source can be a replica that just DIED — the
            # exact scenario the router demos; merge the survivors
            # and note the corpse instead of producing nothing
            print(f"{lbl}: unreachable ({e}) — skipped",
                  file=sys.stderr)
            continue
        if i >= n_positional:
            # fleet sources are named by the router's registry rows
            # ("router" / "replica:<name>"): a source's self-reported
            # process_name carries a host pid, which is ambiguous
            # when replicas share a host — drop it so the registry
            # label wins
            tr["traceEvents"] = [
                e for e in tr["traceEvents"]
                if not (e.get("ph") == "M"
                        and e.get("name") == "process_name")]
        labels.append(lbl)
        traces.append(tr)
    if args.lifecycle:
        for src, trace in zip(labels, traces):
            counts = lifecycle_counts(trace)
            body = ("  ".join(f"{k}={v}" for k, v in
                              sorted(counts.items()))
                    or "(no instant events)")
            print(f"{src}: {body}", file=sys.stderr)
    merged = merge_traces(traces, labels=labels)
    text = json.dumps(merged)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
        n = len(merged["traceEvents"])
        print(f"wrote {args.out}: {n} events from "
              f"{len(traces)} trace(s)", file=sys.stderr)
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
