"""Terminal trace inspector: per-phase time table from a chrome trace.

chrome://tracing is the full viewer, but most "where did tick N's 11 ms
go" questions only need aggregates — this prints, per span name, the
count / total / mean / p50 / p99 duration over every complete-event in
a trace file (a ``/debug/trace`` download, a flight-recorder dump, or
a ``stop_profiler(profile_path=...)`` export), so traces are
inspectable over ssh with nothing but Python.

Usage:
    python tools/trace_view.py trace.json [--cat serving] [--sort total]
"""
from __future__ import annotations

import argparse
import json
import sys


def _percentile(sorted_vals, q):
    """Nearest-rank-with-interpolation percentile over a SORTED list
    (numpy 'linear' semantics — no numpy dependency here: the tool
    must run anywhere a trace file lands)."""
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    rank = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = rank - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def summarize(events, cat=None):
    """Aggregate complete-events (``ph == "X"``) by name.  Returns rows
    of dicts: name, count, total_ms, mean_ms, p50_ms, p99_ms — sorted
    by total descending."""
    groups = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        if cat is not None and ev.get("cat") != cat:
            continue
        groups.setdefault(ev["name"], []).append(
            float(ev.get("dur", 0.0)) / 1e3)  # us -> ms
    rows = []
    for name, durs in groups.items():
        durs.sort()
        rows.append({
            "name": name, "count": len(durs),
            "total_ms": sum(durs),
            "mean_ms": sum(durs) / len(durs),
            "p50_ms": _percentile(durs, 50),
            "p99_ms": _percentile(durs, 99),
        })
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def format_table(rows):
    lines = [f"{'span':<28} {'count':>7} {'total(ms)':>11} "
             f"{'mean(ms)':>10} {'p50(ms)':>10} {'p99(ms)':>10}"]
    for r in rows:
        lines.append(
            f"{r['name']:<28} {r['count']:>7} {r['total_ms']:>11.3f} "
            f"{r['mean_ms']:>10.3f} {r['p50_ms']:>10.3f} "
            f"{r['p99_ms']:>10.3f}")
    return "\n".join(lines)


def wall_summary(events):
    """Per-tick wall time vs summed phase time.  The span table above
    sums every complete-event independently, which silently
    DOUBLE-COUNTS concurrent spans — with the async engine loop, host
    phases (``host.overlap``) run while the device computes, so the
    per-phase totals legitimately exceed wall time.  This summary
    makes that divergence explicit: ``wall_ms`` is the summed duration
    of the ``tick`` spans, ``phase_ms`` the summed duration of every
    other complete-event, ``overlap_ms``/``d2h_wait_ms`` the async
    loop's own attribution spans.  phase/wall > 1 means concurrency
    (work hidden behind device compute), not an accounting bug."""
    wall = phase = overlap = d2h_wait = ragged = 0.0
    ragged_stream = 0.0
    kv_blocks_walked = 0
    allgather = shard_sync = 0.0
    mig_export = mig_wire = mig_import = 0.0
    sup_restart = drain_mig = dequant = 0.0
    lora_swap = stream_emit = 0.0
    off_demote = off_promote = 0.0
    n_ticks = n_ragged = n_ragged_stream = n_allgather = 0
    n_migrations = 0
    n_restarts = n_drain_migs = n_dequants = 0
    n_lora_swaps = n_stream_emits = 0
    n_off_demotes = n_off_promotes = 0
    for ev in events:
        if ev.get("ph") != "X":
            continue
        dur = float(ev.get("dur", 0.0)) / 1e3  # us -> ms
        name = ev.get("name")
        if name == "tick":
            n_ticks += 1
            wall += dur
        else:
            phase += dur
            if name == "host.overlap":
                overlap += dur
            elif name == "decode.d2h_wait":
                d2h_wait += dur
            elif name == "migrate.export":
                # KV block migration legs, broken out per side:
                # export = device->host gather on the source,
                # wire = payload encode/decode in transit,
                # import = host->device scatter + trie adoption on
                # the destination — together, the stream's total
                # off-accelerator time during a migration
                mig_export += dur
                n_migrations += 1
            elif name == "migrate.wire":
                mig_wire += dur
            elif name == "migrate.import":
                mig_import += dur
            elif name == "decode.ragged":
                # Pallas ragged-paged-attention dispatches, GATHER
                # body (Engine(attn_impl="ragged_gather")) — broken
                # out so a trace shows at a glance whether the kernel
                # or the per-shape XLA programs (decode.dispatch)
                # served it
                ragged += dur
                n_ragged += 1
            elif name == "decode.ragged_stream":
                # streaming online-softmax ragged dispatches
                # (Engine(attn_impl="ragged"), the default ragged
                # body) — separate from decode.ragged so an A/B trace
                # prices the two kernel bodies side by side; the
                # span's kv_blocks_walked arg sums each lane's causal
                # horizon, so block-walk cost is attributable per tick
                ragged_stream += dur
                n_ragged_stream += 1
                kv_blocks_walked += int(
                    ev.get("args", {}).get("kv_blocks_walked", 0))
            elif name == "decode.allgather":
                # mesh-sharded engines (Engine(mesh=...)): waiting on
                # the cross-shard psum/all-gather collectives before
                # the tiny replicated d2h — THE tensor-parallel tax,
                # visible per trace instead of smeared into d2h_wait
                allgather += dur
                n_allgather += 1
            elif name == "shard.sync":
                # replicating dirtied cursors/tables to every shard
                shard_sync += dur
            elif name == "supervisor.restart":
                # self-healing fleet legs: restart = the supervisor
                # respawning a dead/wedged replica (boot wait
                # excluded — it only covers the spawn), drain.migrate
                # = a SIGTERM'd replica shipping one live stream to a
                # peer over the migration wire
                sup_restart += dur
                n_restarts += 1
            elif name == "drain.migrate":
                drain_mig += dur
                n_drain_migs += 1
            elif name == "lora.swap":
                # multi-adapter serving: hot-load/unload of a LoRA
                # lane (ring drain + bank .at[lane].set) — the cost
                # of changing the adapter inventory WITHOUT a
                # recompile, visible per swap instead of smeared
                # into the tick gaps
                lora_swap += dur
                n_lora_swaps += 1
            elif name == "stream.emit":
                # token streaming: per-token fan-out from the tick
                # loop to attached SSE sinks — the engine-side cost
                # of live delivery (zero when nobody streams)
                stream_emit += dur
                n_stream_emits += 1
            elif name == "offload.demote":
                # host-RAM KV tier (Engine(kv_host_mb=...)): demote =
                # materializing an evicted block's async gather into
                # the host store at a tick boundary, promote = the
                # admission-gate restore (host payload scattered into
                # fresh device blocks instead of recomputed) — the
                # d2h/h2d price of the second tier, per transfer
                off_demote += dur
                n_off_demotes += 1
            elif name == "offload.promote":
                off_promote += dur
                n_off_promotes += 1
            elif name == "decode.dequant":
                # int8-KV engines (Engine(kv_dtype="int8")): the
                # host-side attribution span of a QUANTIZED dispatch
                # — gather-side dequant rides inside the compiled
                # program, so this is the per-tick cost of serving
                # codes+scales instead of fp blocks, nested inside
                # decode.dispatch/decode.ragged (double-counted in
                # phase_ms like every nested span)
                dequant += dur
                n_dequants += 1
    return {
        "ticks": n_ticks, "wall_ms": wall, "phase_ms": phase,
        "per_tick_wall_ms": wall / n_ticks if n_ticks else float("nan"),
        "per_tick_phase_ms": (phase / n_ticks if n_ticks
                              else float("nan")),
        "overlap_ms": overlap, "d2h_wait_ms": d2h_wait,
        "ragged_ms": ragged, "ragged_dispatches": n_ragged,
        "ragged_stream_ms": ragged_stream,
        "ragged_stream_dispatches": n_ragged_stream,
        "kv_blocks_walked": kv_blocks_walked,
        "allgather_ms": allgather, "allgather_waits": n_allgather,
        "shard_sync_ms": shard_sync,
        "migrations": n_migrations,
        "migrate_export_ms": mig_export,
        "migrate_wire_ms": mig_wire,
        "migrate_import_ms": mig_import,
        "supervisor_restarts": n_restarts,
        "supervisor_restart_ms": sup_restart,
        "drain_migrations": n_drain_migs,
        "drain_migrate_ms": drain_mig,
        "dequant_ms": dequant,
        "dequant_dispatches": n_dequants,
        "lora_swap_ms": lora_swap,
        "lora_swaps": n_lora_swaps,
        "stream_emit_ms": stream_emit,
        "stream_emits": n_stream_emits,
        "offload_demote_ms": off_demote,
        "offload_demotes": n_off_demotes,
        "offload_promote_ms": off_promote,
        "offload_promotes": n_off_promotes,
    }


def format_wall(w):
    lines = [
        f"ticks: {w['ticks']}   wall {w['wall_ms']:.3f} ms   "
        f"summed phases {w['phase_ms']:.3f} ms",
        f"per tick: wall {w['per_tick_wall_ms']:.3f} ms vs phases "
        f"{w['per_tick_phase_ms']:.3f} ms",
        f"host.overlap {w['overlap_ms']:.3f} ms   "
        f"decode.d2h_wait {w['d2h_wait_ms']:.3f} ms",
    ]
    if w.get("ragged_stream_dispatches"):
        per = (w["kv_blocks_walked"] / w["ragged_stream_dispatches"]
               if w["ragged_stream_dispatches"] else 0.0)
        lines.append(
            f"decode.ragged_stream {w['ragged_stream_ms']:.3f} ms "
            f"over {w['ragged_stream_dispatches']} streaming "
            "online-softmax dispatches (attn_impl='ragged')   "
            f"kv blocks walked {w['kv_blocks_walked']} "
            f"({per:.1f}/tick)")
    if w.get("ragged_dispatches"):
        lines.append(
            f"decode.ragged {w['ragged_ms']:.3f} ms over "
            f"{w['ragged_dispatches']} Pallas ragged-kernel "
            "dispatches (gather body, attn_impl='ragged_gather')")
    if w.get("allgather_waits") or w.get("shard_sync_ms"):
        lines.append(
            f"decode.allgather {w['allgather_ms']:.3f} ms over "
            f"{w['allgather_waits']} sharded ticks   shard.sync "
            f"{w['shard_sync_ms']:.3f} ms (mesh-sharded engine: "
            "cross-shard collective wait + cursor replication)")
    if w.get("migrations") or w.get("migrate_import_ms") \
            or w.get("migrate_wire_ms"):
        lines.append(
            f"migrate.export {w['migrate_export_ms']:.3f} ms over "
            f"{w['migrations']} migration(s)   migrate.wire "
            f"{w['migrate_wire_ms']:.3f} ms   migrate.import "
            f"{w['migrate_import_ms']:.3f} ms (KV block migration: "
            "source gather / payload transit / destination adopt)")
    if w.get("dequant_dispatches"):
        lines.append(
            f"decode.dequant {w['dequant_ms']:.3f} ms over "
            f"{w['dequant_dispatches']} quantized dispatches "
            "(kv_dtype='int8': in-program dequant of int8 "
            "codes+scales at gather)")
    if w.get("lora_swaps"):
        lines.append(
            f"lora.swap {w['lora_swap_ms']:.3f} ms over "
            f"{w['lora_swaps']} adapter swap(s) (hot-load/unload "
            "into a bank lane: ring drain + .at[lane].set, zero "
            "recompiles)")
    if w.get("stream_emits"):
        lines.append(
            f"stream.emit {w['stream_emit_ms']:.3f} ms over "
            f"{w['stream_emits']} streamed token(s) (per-token "
            "fan-out to attached SSE sinks)")
    if w.get("offload_demotes") or w.get("offload_promotes"):
        lines.append(
            f"offload.demote {w['offload_demote_ms']:.3f} ms over "
            f"{w['offload_demotes']} block demote(s)   "
            f"offload.promote {w['offload_promote_ms']:.3f} ms over "
            f"{w['offload_promotes']} restore(s) (host-RAM KV tier: "
            "evicted-block spill / admission restore)")
    if w.get("supervisor_restarts") or w.get("drain_migrations"):
        lines.append(
            f"supervisor.restart {w['supervisor_restart_ms']:.3f} ms "
            f"over {w['supervisor_restarts']} respawn(s)   "
            f"drain.migrate {w['drain_migrate_ms']:.3f} ms over "
            f"{w['drain_migrations']} stream(s) (self-healing fleet: "
            "replica respawn + SIGTERM drain handoff)")
    lines += [
        "(phases exceeding wall = spans ran concurrently — e.g. the "
        "async engine loop's",
        " host work hidden behind device compute; the table above "
        "double-counts them)",
    ]
    return "\n".join(lines)


def lifecycle_summary(events):
    """Count instant events (``ph == "i"``) by name — the request
    lifecycle: req.queued / admitted / prefix_adopted / first_token /
    finished / evicted, plus the overload-protection instants
    ``req.preempted`` / ``req.resumed`` / ``req.shed`` (with a
    per-reason breakdown) and ``fault.injected`` / ``engine.watchdog``
    from the chaos harness.  Returns rows of (name, count) sorted by
    count descending; shed/evicted reasons render as
    ``name[reason]``.  (timeline.py's ``lifecycle_counts`` is the
    dict-shaped twin — both tools stay single-file standalone by
    design, so a key-format change must be mirrored there.)"""
    counts = {}
    for ev in events:
        if ev.get("ph") != "i":
            continue
        name = ev.get("name", "?")
        reason = (ev.get("args") or {}).get("reason")
        key = f"{name}[{reason}]" if reason else name
        counts[key] = counts.get(key, 0) + 1
    return sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))


def format_lifecycle(rows):
    lines = [f"{'instant':<28} {'count':>7}"]
    for name, count in rows:
        lines.append(f"{name:<28} {count:>7}")
    return "\n".join(lines)


def load_events(path):
    """Events from a trace file: Catapult object form or bare list."""
    with open(path) as f:
        data = json.load(f)
    events = data["traceEvents"] if isinstance(data, dict) else data
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a chrome trace")
    return events


def main(argv=None):
    p = argparse.ArgumentParser(
        description="per-span-name time table for a chrome trace file")
    p.add_argument("trace", help="trace JSON (object or bare list form)")
    p.add_argument("--cat", default=None,
                   help="only spans of this category (e.g. serving, "
                        "tick, compile, host)")
    p.add_argument("--sort", default="total",
                   choices=("total", "count", "mean", "p50", "p99"),
                   help="sort column (descending; default total)")
    p.add_argument("--wall", action="store_true",
                   help="append a per-tick wall-time vs summed-phase "
                        "summary (concurrent spans — async engine "
                        "overlap — make the two diverge; the table "
                        "alone double-counts them)")
    p.add_argument("--lifecycle", action="store_true",
                   help="append an instant-event count table (request "
                        "lifecycle incl. req.preempted / req.resumed "
                        "/ req.shed[reason], fault.injected, "
                        "engine.watchdog)")
    args = p.parse_args(argv)
    events = load_events(args.trace)
    rows = summarize(events, cat=args.cat)
    key = {"total": "total_ms", "count": "count", "mean": "mean_ms",
           "p50": "p50_ms", "p99": "p99_ms"}[args.sort]
    rows.sort(key=lambda r: -r[key])
    if not rows and not args.lifecycle:
        print("no complete-events matched", file=sys.stderr)
        return 1
    if rows:
        print(format_table(rows))
    if args.wall:
        print()
        print(format_wall(wall_summary(events)))
    if args.lifecycle:
        life = lifecycle_summary(events)
        print()
        if life:
            print(format_lifecycle(life))
        else:
            print("no instant events", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
