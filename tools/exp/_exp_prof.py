"""Profile one GPT-2 train step on TPU; dump op-level cost breakdown."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import numpy as np

def main():
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep
    paddle.seed(0)
    model = GPTModel.from_config("gpt2-medium", dropout=0.1,
                                 fused_loss=True)
    model.to(dtype="bfloat16")
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters())
    step = TrainStep(model, opt, loss_fn=None)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50304, (8, 1025)).astype(np.int32)
    x, y = ids[:, :-1], ids[:, 1:]
    step.step([x, y]).numpy()
    # compiled-cost analysis instead of a trace: what does XLA think?
    fn = next(iter(step._compiled.values()))
    # measure pure device time
    t0 = time.perf_counter()
    for _ in range(20):
        loss = step.step([x, y])
    loss.numpy()
    dt = (time.perf_counter() - t0) / 20
    print(f"step {dt*1000:.1f} ms  ({8*1024/dt:.0f} tok/s)")
    flops_fwd_bwd = 6 * 355e6 * 8 * 1024            # param matmuls
    att = 12 * 8 * 1024 * 1024 * 1024 * 24          # attention matmuls
    total = flops_fwd_bwd + att
    print(f"model flops/step ~{total/1e12:.1f} TF -> "
          f"{total/dt/1e12:.0f} TF/s vs 197 peak "
          f"({total/dt/197e12*100:.0f}% MFU)")

main()
