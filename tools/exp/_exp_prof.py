"""Profile one GPT-2 train step; dump an op-level time breakdown.

VERDICT round-2 #3: switch MFU work from sweep-driven to trace-driven.
Captures a jax.profiler trace (XPlane) of steady-state steps and prints
the top op buckets by device time (parsed from the .xplane.pb via
tensorboard_plugin_profile's protos; falls back to printing the trace
path if the proto schema is unavailable), plus the cost-model MFU.

Usage: python tools/exp/_exp_prof.py [--trace-dir /tmp/xplane_r3]
"""
import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np


def _xplane_pb2():
    """Vendored minimal XPlane schema (tools/exp/proto/xplane.proto),
    protoc-generated on demand — the tensorboard plugin's bundled pb2s
    predate this protobuf runtime."""
    proto_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "proto")
    if not os.path.exists(os.path.join(proto_dir, "xplane_pb2.py")):
        import subprocess
        subprocess.run(["protoc", "--python_out", proto_dir,
                        "--proto_path", proto_dir,
                        os.path.join(proto_dir, "xplane.proto")],
                       check=True)
    sys.path.insert(0, proto_dir)
    import xplane_pb2
    return xplane_pb2


def parse_xplane(trace_dir):
    """Per-op device-time buckets from the trace's dominant op line.

    XPlane lines OVERLAP ('XLA Modules' span their ops, 'Steps' span
    everything), so summing across lines would double-count — the
    rollup picks the 'XLA Ops' line when present, else buckets per line
    and reports the single line with the largest total."""
    try:
        xplane_pb2 = _xplane_pb2()
    except Exception:
        return None
    paths = glob.glob(os.path.join(
        trace_dir, "**", "*.xplane.pb"), recursive=True)
    if not paths:
        return None
    xspace = xplane_pb2.XSpace()
    with open(sorted(paths)[-1], "rb") as f:
        xspace.ParseFromString(f.read())

    def line_buckets(plane, line):
        ev_meta = {k: v.name for k, v in plane.event_metadata.items()}
        buckets = {}
        for ev in line.events:
            op = ev_meta.get(ev.metadata_id, "?")
            buckets[op] = buckets.get(op, 0) + ev.duration_ps
        return buckets

    device = [p for p in xspace.planes
              if "tpu" in p.name.lower() or "device" in p.name.lower()]
    planes = device or [p for p in xspace.planes if p.lines]
    best = {}
    for plane in planes:
        for line in plane.lines:
            b = line_buckets(plane, line)
            name = (line.display_name or line.name).lower()
            if "xla ops" in name or "xla op" == name:
                best = b
                break
            if sum(b.values()) > sum(best.values() or [0]):
                best = b
        else:
            continue
        break
    total = sum(best.values())
    if not total:
        return None
    top = sorted(best.items(), key=lambda kv: -kv[1])[:25]

    import re as _re

    def category(op):
        """Semantic bucket from the HLO op text — so the rollup covers
        100% of device time, not just the top-N individual ops.  The
        OPCODE is the token after '= <type>' (matching on the whole
        line would misbucket fusions whose bodies mention other ops)."""
        name = op.split(" = ")[0].strip("%").lower()
        m = _re.search(r"= \S+?\s+([\w-]+)\(", op)
        opcode = (m.group(1) if m else name.split(".")[0]).lower()
        if opcode == "while":
            return "while-loops (fused-CE scan & co)"
        if opcode == "custom-call":
            return "custom calls (pallas)"
        if opcode in ("dot", "convolution") or "convolution" in name \
                or name.startswith("dot"):
            return "matmul/conv fusions"
        if opcode in ("all-reduce", "all-gather", "reduce-scatter",
                      "collective-permute", "all-to-all"):
            return "collectives"
        if opcode in ("copy", "bitcast", "transpose", "reshape",
                      "copy-start", "copy-done"):
            return "copies/layout"
        if opcode.startswith("rng"):
            return "rng"
        if opcode == "fusion":
            if "dynamic-update-slice" in name or "dynamic-slice" in name:
                return "slice/update fusions"
            if "reduce" in name:
                return "reduction fusions"
            return "elementwise/other fusions"
        return "other (" + opcode + ")" if opcode else "other"

    cats = {}
    for k, v in best.items():
        c = category(k)
        e = cats.setdefault(c, {"ms": 0.0, "count": 0, "top_op": k,
                                "top_ms": 0.0})
        e["ms"] += v / 1e9
        e["count"] += 1
        if v / 1e9 > e["top_ms"]:
            e["top_ms"] = round(v / 1e9, 3)
            e["top_op"] = k.split(" = ")[0].strip("%")
    categories = sorted(
        ({"category": c, "ms": round(e["ms"], 2),
          "pct": round(100 * e["ms"] * 1e9 / total, 1),
          "ops": e["count"], "top_op": e["top_op"],
          "top_ms": e["top_ms"]} for c, e in cats.items()),
        key=lambda d: -d["ms"])
    return {
        "total_device_ms": round(total / 1e9, 2),
        "categories": categories,
        "top": [{"op": k.split(" = ")[0].strip("%"),
                 "ms": round(v / 1e9, 3),
                 "pct": round(100 * v / total, 1)} for k, v in top],
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-dir", default="/tmp/xplane_r3")
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    import jax
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep

    paddle.seed(0)
    on_tpu = jax.default_backend() != "cpu"
    model = GPTModel.from_config("gpt2-medium", dropout=0.1,
                                 fused_loss=True)
    if on_tpu:
        model.to(dtype="bfloat16")
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters())
    step = TrainStep(model, opt, loss_fn=None)
    rng = np.random.RandomState(0)
    batch, seq = (8, 1024) if on_tpu else (2, 128)
    ids = rng.randint(0, 50304, (batch, seq + 1)).astype(np.int32)
    x, y = ids[:, :-1], ids[:, 1:]
    step.step([x, y]).numpy()  # compile

    # steady-state timing
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = step.step([x, y])
    loss.numpy()
    dt = (time.perf_counter() - t0) / args.steps
    out = {"step_ms": round(dt * 1000, 1),
           "tokens_per_s": round(batch * seq / dt, 1)}
    flops = 6 * 355e6 * batch * seq + 12 * batch * seq * seq * 1024 * 24
    out["model_tflops_per_step"] = round(flops / 1e12, 2)
    if on_tpu:
        out["mfu_pct_vs_197tf"] = round(flops / dt / 197e12 * 100, 1)

    # trace 3 steps
    with jax.profiler.trace(args.trace_dir):
        for _ in range(3):
            loss = step.step([x, y])
        loss.numpy()
    out["trace_dir"] = args.trace_dir
    top = parse_xplane(args.trace_dir)
    if top is not None:
        out["top_ops"] = top
    else:
        out["top_ops"] = ("xplane parse unavailable - open trace_dir in "
                          "tensorboard's profile plugin")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
