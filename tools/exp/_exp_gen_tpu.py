"""Compiled-generation throughput on hardware (VERDICT round-2 #8).

Measures GPT-2 345M prefill tokens/s and decode tokens/s at b1 and b8
through `GPTModel.generate(compiled=True)` (one jitted donated-buffer
decode step), plus an eager-vs-compiled greedy token-parity assert on a
small config.  Round 2 recorded 13-22x eager on the CPU backend only;
this records the TPU numbers BASELINE.md is missing.

Usage: python tools/exp/_exp_gen_tpu.py  [--config gpt2-medium]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np


def measure(model, batch, prompt_len, new_tokens, vocab, mode=True):
    """mode=True: per-token jitted step.  mode="fused": whole decode =
    one lax.scan jit (one dispatch, one sync — the remote-device mode)."""
    import paddle_tpu as paddle
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, vocab, (batch, prompt_len)).astype(np.int32))
    # warmup compiles prefill + decode step/scan for BOTH measured token
    # counts (fused: the scan length is part of the program)
    model.generate(ids, max_new_tokens=1, compiled=mode)
    model.generate(ids, max_new_tokens=new_tokens, compiled=mode)
    # prefill: a generate that decodes ONE token — dominated by the
    # prompt pass at these lengths
    t0 = time.perf_counter()
    model.generate(ids, max_new_tokens=1, compiled=mode).numpy()
    t_prefill = time.perf_counter() - t0
    # decode: long continuation minus the measured 1-token call — both
    # share the same prefill program, so the difference is pure decode
    t0 = time.perf_counter()
    out = model.generate(ids, max_new_tokens=new_tokens, compiled=mode)
    np.asarray(out.numpy())
    t_total = time.perf_counter() - t0
    # through a jittery tunnel the 1-token call can measure SLOWER than
    # the full call — the subtraction is then meaningless: report null
    # and let end_to_end_s (the robust number) speak
    t_decode = t_total - t_prefill
    return {
        "batch": batch, "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "prefill_tokens_per_s": round(batch * prompt_len / t_prefill, 1),
        "decode_tokens_per_s": round(
            batch * (new_tokens - 1) / t_decode, 1)
        if t_decode > 1e-3 else None,
        "end_to_end_s": round(t_total, 3),
        "new_tokens_per_s_e2e": round(
            batch * new_tokens / t_total, 1),
    }


def parity_check():
    """Greedy eager == compiled token-for-token on a small config."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTModel
    paddle.seed(0)
    m = GPTModel.from_config("tiny", dropout=0.0)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(1).randint(0, 128, (2, 8)).astype(np.int32))
    eager = m.generate(ids, max_new_tokens=12, compiled=False).numpy()
    comp = m.generate(ids, max_new_tokens=12, compiled=True).numpy()
    return bool(np.array_equal(eager, comp))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="gpt2-medium")
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--new-tokens", type=int, default=128)
    args = ap.parse_args()

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTModel, GPT_CONFIGS

    out = {"backend": jax.default_backend(), "config": args.config,
           "greedy_parity": parity_check()}
    paddle.seed(0)
    model = GPTModel.from_config(args.config, dropout=0.0)
    if jax.default_backend() != "cpu":
        model.to(dtype="bfloat16")
    model.eval()
    vocab = GPT_CONFIGS[args.config]["vocab_size"]
    for batch in (1, 8):
        out[f"b{batch}"] = measure(model, batch, args.prompt_len,
                                   args.new_tokens, vocab)
        print(json.dumps({f"b{batch}": out[f"b{batch}"]}), flush=True)
        out[f"b{batch}_fused"] = measure(model, batch, args.prompt_len,
                                         args.new_tokens, vocab,
                                         mode="fused")
        print(json.dumps({f"b{batch}_fused": out[f"b{batch}_fused"]}),
              flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
