"""Single-chip MFU sweep: batch x remat-policy on GPT-2 345M (VERDICT #7)."""
import json, sys, time, os
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))
import numpy as np

def main():
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep

    assert jax.default_backend() != "cpu"
    seq, vocab = 1024, 50304
    rng = np.random.RandomState(0)
    results = []
    configs = [
        (8,  False, None),
        (10, False, None),
        (12, True, "dots"),
        (16, True, "dots"),
        (24, True, "dots"),
        (16, True, "full"),
        (32, True, "dots"),
    ]
    for batch, remat, policy in configs:
        try:
            paddle.seed(0)
            model = GPTModel.from_config("gpt2-medium", dropout=0.1,
                                         fused_loss=True,
                                         use_recompute=remat,
                                         recompute_policy=policy)
            model.to(dtype="bfloat16")
            opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                                  parameters=model.parameters())
            step = TrainStep(model, opt, loss_fn=None)
            ids = rng.randint(0, vocab, (batch, seq + 1)).astype(np.int32)
            x, y = ids[:, :-1], ids[:, 1:]
            xd = jax.device_put(x, step._data_sharding(x.shape))
            yd = jax.device_put(y, step._data_sharding(y.shape))
            loss = step.step([xd, yd]); loss.numpy()
            t0 = time.perf_counter()
            for _ in range(15):
                loss = step.step([xd, yd])
            loss.numpy()
            tps = batch * seq * 15 / (time.perf_counter() - t0)
            results.append((batch, remat, policy, round(tps, 1)))
            print(f"b{batch} remat={remat} policy={policy}: {tps:,.0f} tok/s",
                  flush=True)
            del step, model, opt
        except Exception as e:
            print(f"b{batch} remat={remat} policy={policy}: FAIL "
                  f"{type(e).__name__}: {str(e)[:200]}", flush=True)
    print(json.dumps(results))

main()
