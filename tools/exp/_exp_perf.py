"""Scratch perf experiment: GPT-2 345M step time vs batch size."""
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))
import numpy as np

def run(batch, seq=1024, steps=10, fused_loss=True, flash=False):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.nn.functional import attention as att
    att.FLASH_MIN_SEQ = 0 if flash else 10**9
    if os.environ.get("EXP_ATT_REMAT", "0") == "1":
        orig = att._reference_attention

        def remat_ref(q, k, v, mask=None, scale=None, is_causal=False):
            return jax.checkpoint(
                lambda qq, kk, vv: orig(qq, kk, vv, mask, scale,
                                        is_causal))(q, k, v)

        att._reference_attention = remat_ref
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep
    paddle.seed(0)
    remat = os.environ.get("EXP_REMAT", "0") == "1"
    model = GPTModel.from_config("gpt2-medium", dropout=0.1,
                                 fused_loss=fused_loss,
                                 use_recompute=remat)
    model.to(dtype="bfloat16")
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters())
    step = TrainStep(model, opt, loss_fn=None)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50304, (batch, seq + 1)).astype(np.int32)
    x, y = ids[:, :-1], ids[:, 1:]
    loss = step.step([x, y]); loss.numpy()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step.step([x, y])
    loss.numpy()
    dt = time.perf_counter() - t0
    tps = batch * seq * steps / dt
    print(f"batch={batch} seq={seq} fused={fused_loss} flash={flash}: "
          f"{tps:.0f} tok/s  ({dt/steps*1000:.1f} ms/step)", flush=True)
    return tps

if __name__ == "__main__":
    flash = os.environ.get("EXP_FLASH", "0") == "1"
    for b in (int(a) for a in sys.argv[1:] or ["8", "16", "32"]):
        try:
            run(b, flash=flash)
        except Exception as e:
            print(f"batch={b} flash={flash}: FAILED {type(e).__name__}",
                  flush=True)
