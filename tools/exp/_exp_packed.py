"""Packed vs padded GPT throughput at realistic document skew (TPU).

Compares real-token throughput of (a) bucketed padded-dense batches vs
(b) token-budget packed batches with segment-id flash masking, on the
BASELINE round-3 lognormal corpus. The packed path should win by
roughly the padding-waste ratio (~17% at this skew) at long budgets
where flash engages.

Usage: python tools/exp/_exp_packed.py [--budget 4096] [--steps 12]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--docs", type=int, default=2048)
    # the dev tunnel's compile service dies after ~10 back-to-back
    # 345M+remat compiles: --leg runs one leg per process, --ladder pow2
    # needs 8 compiles instead of the x1.5 ladder's 13
    ap.add_argument("--leg", choices=("both", "packed", "padded"),
                    default="both")
    ap.add_argument("--ladder", choices=("x15", "pow2"), default="x15")
    args = ap.parse_args()

    import jax
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.io.bucketing import (POW2_BUCKETS,
                                         TokenBudgetBatchSampler,
                                         bucket_for, DEFAULT_BUCKETS)
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep
    from _exp_ragged import make_corpus

    on_tpu = jax.default_backend() != "cpu"
    cfg = "gpt2-medium" if on_tpu else "tiny"
    budget = args.budget if on_tpu else 128
    docs, lengths = make_corpus(args.docs, max_len=budget)
    out = {"backend": jax.default_backend(), "budget": budget}

    MAX_ROWS = 64          # docs per packed row (doc_lens width)
    ROWS_PER_STEP = 8      # packed rows per step == padded batch rows:
    #                        both legs then move ~8 x budget tokens/step,
    #                        isolating packing from batch-size/MFU effects

    class PackedGPT(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.gpt = GPTModel.from_config(
                cfg, dropout=0.1, max_position=budget, fused_loss=True,
                # 8 rows x budget-4096 = 32k tokens/step: activations
                # (24 x 256MB MLP intermediates alone) exceed HBM
                # without remat — same recipe any long-seq run uses
                use_recompute=budget >= 2048)

        def forward(self, ids, doc_lens, labels):
            return self.gpt(ids, labels=labels, doc_lens=doc_lens)

    def run_packed():
        paddle.seed(0)
        model = PackedGPT()
        if on_tpu:
            model.to(dtype="bfloat16")
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        step = TrainStep(model, opt, loss_fn=None)

        class DS:
            def __getitem__(self, i):
                return (docs[i],)

            def __len__(self):
                return len(docs)

        sampler = TokenBudgetBatchSampler(
            DS(), token_budget=budget, max_batch_size=MAX_ROWS,
            length_fn=lambda i: int(lengths[i]), shuffle=True)
        rows = list(sampler)
        feeds = []
        for s0 in range(0, len(rows) - ROWS_PER_STEP + 1,
                        ROWS_PER_STEP):
            ids = np.zeros((ROWS_PER_STEP, budget), np.int32)
            dl = np.zeros((ROWS_PER_STEP, MAX_ROWS), np.int32)
            real = 0
            for r, b in enumerate(rows[s0:s0 + ROWS_PER_STEP]):
                off = 0
                for j, i in enumerate(b):
                    d = docs[i][:int(lengths[i])]  # corpus stores len+1
                    ids[r, off:off + len(d)] = d
                    dl[r, j] = len(d)
                    off += len(d)
                real += off
            labels = np.concatenate(
                [ids[:, 1:], np.zeros((ROWS_PER_STEP, 1), np.int32)],
                axis=1).astype(np.int64)
            feeds.append((ids, dl, labels, real))
            if len(feeds) >= args.steps + 1:
                break
        step.step(list(feeds[0][:3])).numpy()  # compile + SYNC
        t0 = time.perf_counter()
        real = 0
        for f in feeds[1:args.steps + 1]:
            loss = step.step(list(f[:3]))
            real += f[3]
        loss.numpy()
        dt = time.perf_counter() - t0
        return round(real / dt, 1)

    def run_padded():
        paddle.seed(0)
        model = GPTModel.from_config(cfg, dropout=0.1, fused_loss=True,
                                     max_position=budget,
                                     use_recompute=budget >= 2048)
        if on_tpu:
            model.to(dtype="bfloat16")
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        step = TrainStep(model, opt, loss_fn=None)
        base = POW2_BUCKETS if args.ladder == "pow2" else DEFAULT_BUCKETS
        ladder = tuple(b for b in base if b <= budget)
        if budget not in ladder:
            ladder = ladder + (budget,)
        # SAME corpus, SAME shuffle-everything sampling as the packed
        # leg (sorting would benchmark only the tail and hide the
        # population's padding waste)
        rs = np.random.RandomState(0)
        order = rs.permutation(len(docs))
        batches = []
        for s0 in range(0, len(order) - ROWS_PER_STEP + 1,
                        ROWS_PER_STEP):
            idx = order[s0:s0 + ROWS_PER_STEP]
            L = bucket_for(int(max(lengths[i] for i in idx)), ladder)
            x = np.zeros((ROWS_PER_STEP, L), np.int32)
            y = np.zeros((ROWS_PER_STEP, L), np.int64)
            real = 0
            for r, i in enumerate(idx):
                d = docs[i]
                x[r, :len(d) - 1] = d[:-1]
                y[r, :len(d) - 1] = d[1:]
                real += len(d) - 1
            batches.append((x, y, real))
        # pre-compile EVERY bucket shape outside the timed window (a
        # 20-40s TPU compile inside it would deflate the denominator)
        seen = set()
        # only the TIMED batches' buckets need pre-compiling (compiling
        # the whole corpus's ladder burned 13 compiles; the dev tunnel's
        # compile service dies after ~6-10 of this program class)
        for x, y, _ in batches[:args.steps]:
            if x.shape[1] not in seen:
                seen.add(x.shape[1])
                print(json.dumps({"padded_compile_L": x.shape[1]}),
                      file=sys.stderr, flush=True)
                step.step([x, y]).numpy()
        t0 = time.perf_counter()
        real = 0
        for x, y, r in batches[:args.steps]:
            loss = step.step([x, y])
            real += r
        loss.numpy()
        dt = time.perf_counter() - t0
        return round(real / dt, 1)

    # flush per leg: a device crash in one leg must not lose the other
    # (observed: TPU worker fault in the padded leg after packed passed)
    if args.leg in ("both", "packed"):
        out["packed_real_tokens_per_s"] = run_packed()
        print(json.dumps({"packed_real_tokens_per_s":
                          out["packed_real_tokens_per_s"]}), flush=True)
    if args.leg in ("both", "padded"):
        out["padded_real_tokens_per_s"] = run_padded()
        print(json.dumps({"padded_real_tokens_per_s":
                          out["padded_real_tokens_per_s"]}), flush=True)
    if args.leg == "both":
        out["packed_vs_padded"] = round(
            out["packed_real_tokens_per_s"]
            / max(out["padded_real_tokens_per_s"], 1e-9), 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
