"""Packed vs padded GPT throughput at realistic document skew (TPU).

Compares real-token throughput of (a) bucketed padded-dense batches vs
(b) token-budget packed batches with segment-id flash masking, on the
BASELINE round-3 lognormal corpus. The packed path should win by
roughly the padding-waste ratio (~17% at this skew) at long budgets
where flash engages.

Usage: python tools/exp/_exp_packed.py [--budget 4096] [--steps 12]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--docs", type=int, default=2048)
    args = ap.parse_args()

    import jax
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.io.bucketing import (TokenBudgetBatchSampler,
                                         bucket_for, DEFAULT_BUCKETS)
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep
    from _exp_ragged import make_corpus

    on_tpu = jax.default_backend() != "cpu"
    cfg = "gpt2-medium" if on_tpu else "tiny"
    budget = args.budget if on_tpu else 128
    docs, lengths = make_corpus(args.docs, max_len=budget)
    out = {"backend": jax.default_backend(), "budget": budget}

    class PackedGPT(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.gpt = GPTModel.from_config(
                cfg, dropout=0.1, max_position=budget)

        def forward(self, ids, doc_lens, labels):
            return self.gpt(ids, labels=labels, doc_lens=doc_lens)

    def run_packed():
        paddle.seed(0)
        model = PackedGPT()
        if on_tpu:
            model.to(dtype="bfloat16")
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        step = TrainStep(model, opt, loss_fn=None)

        class DS:
            def __getitem__(self, i):
                return (docs[i],)

            def __len__(self):
                return len(docs)

        sampler = TokenBudgetBatchSampler(
            DS(), token_budget=budget, max_batch_size=64,
            length_fn=lambda i: int(lengths[i]), shuffle=True)
        batches = list(sampler)[:args.steps + 2]
        feeds = []
        for b in batches:
            ids = np.zeros((1, budget), np.int32)
            dl = np.zeros((1, 64), np.int32)
            off = 0
            for j, i in enumerate(b):
                d = docs[i][:int(lengths[i])]  # corpus stores len+1
                ids[0, off:off + len(d)] = d
                dl[0, j] = len(d)
                off += len(d)
            labels = np.concatenate([ids[0, 1:], [0]])[None, :] \
                .astype(np.int64)
            feeds.append((ids, dl, labels, off))
        step.step(list(feeds[0][:3]))  # compile
        t0 = time.perf_counter()
        real = 0
        for f in feeds[1:args.steps + 1]:
            loss = step.step(list(f[:3]))
            real += f[3]
        loss.numpy()
        dt = time.perf_counter() - t0
        return round(real / dt, 1)

    def run_padded():
        paddle.seed(0)
        model = GPTModel.from_config(cfg, dropout=0.1, fused_loss=True,
                                     max_position=budget)
        if on_tpu:
            model.to(dtype="bfloat16")
        opt = optimizer.AdamW(learning_rate=1e-4,
                              parameters=model.parameters())
        step = TrainStep(model, opt, loss_fn=None)
        # bucketed batches of 8 rows padded to the bucket
        order = np.argsort(lengths)[::-1]
        t0 = None
        real = done = 0
        for s0 in range(0, len(order), 8):
            idx = order[s0:s0 + 8]
            L = bucket_for(int(max(lengths[i] for i in idx)),
                           tuple(b for b in DEFAULT_BUCKETS
                                 if b <= budget) + (budget,))
            x = np.zeros((8, L), np.int32)
            y = np.zeros((8, L), np.int64)
            for r, i in enumerate(idx[:8]):
                d = docs[i]
                x[r, :len(d) - 1] = d[:-1]
                y[r, :len(d) - 1] = d[1:]
            loss = step.step([x, y])
            if t0 is None:  # first step = compile; start timing after
                loss.numpy()
                t0 = time.perf_counter()
                continue
            real += int(sum(lengths[i] for i in idx))
            done += 1
            if done >= args.steps:
                break
        loss.numpy()
        dt = time.perf_counter() - t0
        return round(real / dt, 1)

    out["packed_real_tokens_per_s"] = run_packed()
    out["padded_real_tokens_per_s"] = run_padded()
    out["packed_vs_padded"] = round(
        out["packed_real_tokens_per_s"]
        / max(out["padded_real_tokens_per_s"], 1e-9), 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
