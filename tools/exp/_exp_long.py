"""Long-context flash tuning: seq 4096, batch 2."""
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))
import numpy as np

def run(blocks, steps=6, seq=4096, batch=2):
    import paddle_tpu as paddle
    from paddle_tpu.nn.functional import attention as att
    att.FLASH_MIN_SEQ = 2048
    if blocks:
        from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes
        bq, bk = blocks
        att.FLASH_BLOCK_SIZES = BlockSizes(
            block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
            block_q_major_dkv=bq, block_k_major_dkv=bk,
            block_k_dkv=bk, block_q_dkv=bq,
            block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq)
    else:
        att.FLASH_BLOCK_SIZES = None
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep
    paddle.seed(0)
    model = GPTModel.from_config("gpt2-medium", dropout=0.1,
                                 fused_loss=True, max_position=seq)
    model.to(dtype="bfloat16")
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters())
    step = TrainStep(model, opt, loss_fn=None)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50304, (batch, seq + 1)).astype(np.int32)
    x, y = ids[:, :-1], ids[:, 1:]
    loss = step.step([x, y]); loss.numpy()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step.step([x, y])
    loss.numpy()
    dt = time.perf_counter() - t0
    tag = f"bq={blocks[0]} bk={blocks[1]}" if blocks else "default"
    print(f"seq={seq} batch={batch} {tag}: "
          f"{batch*seq*steps/dt:.0f} tok/s", flush=True)

if __name__ == "__main__":
    for blocks in (None, (1024, 512), (2048, 512), (1024, 1024)):
        try:
            run(blocks)
        except Exception as e:
            print(f"{blocks}: FAILED {type(e).__name__}: {e}"[:200],
                  flush=True)
