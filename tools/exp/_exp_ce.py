"""Fused-CE chunk size sweep at the bench config."""
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))
import numpy as np

def run(chunk, steps=10):
    import paddle_tpu as paddle
    from paddle_tpu.nn.functional import loss as L
    orig = L.fused_linear_cross_entropy
    # NOTE: superseded by _exp_ce_chunk.py (proper fused_loss_chunk ctor
    # arg); signature kept in sync with the real functional
    def patched(hidden, weight, labels, chunk_size=128,
                ignore_index=None, name=None):
        return orig(hidden, weight, labels, chunk_size=chunk,
                    ignore_index=ignore_index)
    L.fused_linear_cross_entropy = patched
    import paddle_tpu.models.gpt as gpt
    gpt.F.fused_linear_cross_entropy = patched
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep
    paddle.seed(0)
    model = GPTModel.from_config("gpt2-medium", dropout=0.1,
                                 fused_loss=True)
    model.to(dtype="bfloat16")
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters())
    step = TrainStep(model, opt, loss_fn=None)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50304, (8, 1025)).astype(np.int32)
    x, y = ids[:, :-1], ids[:, 1:]
    step.step([x, y]).numpy()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step.step([x, y])
    loss.numpy()
    dt = time.perf_counter() - t0
    print(f"chunk={chunk}: {8*1024*steps/dt:.0f} tok/s", flush=True)

if __name__ == "__main__":
    for c in (256, 512, 64):
        try:
            run(c)
        except Exception as e:
            print(f"chunk={c}: FAILED {type(e).__name__}", flush=True)
