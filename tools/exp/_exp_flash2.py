"""Flash block-size tuning at seq 1024, batch 8."""
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))
import numpy as np

def run(block_q, block_k, steps=10):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.nn.functional import attention as att
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes
    att.FLASH_MIN_SEQ = 0
    att.FLASH_BLOCK_SIZES = BlockSizes(
        block_q=block_q, block_k_major=block_k, block_k=block_k,
        block_b=1,
        block_q_major_dkv=block_q, block_k_major_dkv=block_k,
        block_k_dkv=block_k, block_q_dkv=block_q,
        block_k_major_dq=block_k, block_k_dq=block_k,
        block_q_dq=block_q)
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep
    paddle.seed(0)
    model = GPTModel.from_config("gpt2-medium", dropout=0.1,
                                 fused_loss=True)
    model.to(dtype="bfloat16")
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters())
    step = TrainStep(model, opt, loss_fn=None)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50304, (8, 1025)).astype(np.int32)
    x, y = ids[:, :-1], ids[:, 1:]
    loss = step.step([x, y]); loss.numpy()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step.step([x, y])
    loss.numpy()
    dt = time.perf_counter() - t0
    print(f"bq={block_q} bk={block_k}: {8*1024*steps/dt:.0f} tok/s",
          flush=True)

if __name__ == "__main__":
    for bq, bk in [(512, 1024), (1024, 512), (512, 512)]:
        try:
            run(bq, bk)
        except Exception as e:
            print(f"bq={bq} bk={bk}: FAILED {type(e).__name__}", flush=True)

