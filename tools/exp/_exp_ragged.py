"""Ragged-skew stress: pathological length distribution through
bucketing -> DataLoader -> TrainStep (VERDICT round-2 missing #1 evidence).

The dense+lengths reduction (COVERAGE.md: LoDTensor -> padded dense +
bucketing) must hold up under realistic document-length skew.  This
drives an open-web-like lognormal length distribution end-to-end and
records, per padding strategy:
  - compile count (distinct padded shapes == XLA step variants)
  - padding waste (1 - real tokens / padded tokens)
  - wall tokens/s through TrainStep (real tokens, total wall incl. compiles)

Strategies: naive global-max padding, per-batch-max padding (the
recompile storm), bucketed padding at several bucket ladders.

Usage: python tools/exp/_exp_ragged.py [--docs 2048] [--steps-cap 999999]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np


def make_corpus(n_docs, seed=0, max_len=2048):
    """Open-web-like doc lengths: lognormal (median ~170, heavy tail),
    clipped to [8, max_len]."""
    rs = np.random.RandomState(seed)
    lengths = np.clip(np.exp(rs.normal(5.14, 1.1, n_docs)), 8,
                      max_len).astype(np.int64)
    docs = [rs.randint(0, 50304, (int(l) + 1,)).astype(np.int32)
            for l in lengths]
    return docs, lengths


LADDERS = {
    "pow2 (default)": (32, 64, 128, 256, 512, 1024, 2048),
    "x1.5 tile-aligned": (32, 48, 72, 112, 168, 248, 368, 552, 824,
                          1280, 1920, 2048),
    "quantile-8": None,  # computed from the data below
}


def quantile_ladder(lengths, k=8, max_len=2048):
    qs = np.quantile(lengths, np.linspace(0, 1, k + 1)[1:])
    ladder = sorted({int(np.ceil(q / 8) * 8) for q in qs} | {max_len})
    return tuple(ladder)


def run_strategy(docs, lengths, batches_of_indices, pad_len_fn, batch,
                 steps_cap, label):
    """pad_len_fn(batch_lengths) -> padded length for that batch."""
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep

    paddle.seed(0)
    model = GPTModel.from_config("tiny", dropout=0.0, fused_loss=True,
                                 max_position=2048)
    opt = optimizer.AdamW(learning_rate=1e-4,
                          parameters=model.parameters())
    step = TrainStep(model, opt, loss_fn=None)

    shapes = set()
    real_tokens = padded_tokens = 0
    t0 = time.perf_counter()
    n_steps = 0
    for idx_batch in batches_of_indices:
        if n_steps >= steps_cap:
            break
        blens = lengths[idx_batch]
        L = int(pad_len_fn(blens))
        x = np.zeros((len(idx_batch), L), np.int32)
        y = np.zeros((len(idx_batch), L), np.int32)
        for r, i in enumerate(idx_batch):
            d = docs[i][:L + 1]
            x[r, :len(d) - 1] = d[:-1]
            y[r, :len(d) - 1] = d[1:]
        shapes.add(x.shape)
        loss = step.step([x, y])
        real_tokens += int(blens.sum())
        padded_tokens += x.size
        n_steps += 1
    loss.numpy()
    dt = time.perf_counter() - t0
    return {
        "strategy": label,
        "steps": n_steps,
        "compiles": len(shapes),
        "padding_waste_pct": round(100 * (1 - real_tokens /
                                          max(padded_tokens, 1)), 1),
        "real_tokens_per_s": round(real_tokens / dt, 1),
        "wall_s": round(dt, 1),
    }


def analytic(lengths, batches_of_indices, pad_len_fn, label):
    """Padding waste + compile count are properties of the BATCHING, not
    the model — computed exactly over the full corpus without running."""
    shapes = set()
    real = padded = 0
    for idx_batch in batches_of_indices:
        blens = lengths[idx_batch]
        L = int(pad_len_fn(blens))
        shapes.add((len(idx_batch), L))
        real += int(blens.sum())
        padded += len(idx_batch) * L
    return {"strategy": label, "steps": len(batches_of_indices),
            "compiles": len(shapes),
            "padding_waste_pct": round(100 * (1 - real / padded), 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--docs", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--steps-cap", type=int, default=10 ** 9)
    ap.add_argument("--analytic-only", action="store_true",
                    help="waste/compile-count table only (no model runs)")
    args = ap.parse_args()

    docs, lengths = make_corpus(args.docs)
    print(json.dumps({"corpus": {
        "docs": args.docs, "median_len": int(np.median(lengths)),
        "p90": int(np.quantile(lengths, 0.9)),
        "max": int(lengths.max()),
        "total_tokens": int(lengths.sum())}}), flush=True)

    from paddle_tpu.io.bucketing import BucketedBatchSampler, bucket_for

    class LenDataset:
        def __init__(self):
            self.lengths = lengths

        def __getitem__(self, i):
            return docs[i]

        def __len__(self):
            return len(docs)

    ds = LenDataset()
    LADDERS["quantile-8"] = quantile_ladder(lengths)

    def strategies():
        order = np.arange(args.docs)
        yield ([order[i:i + args.batch]
                for i in range(0, args.docs, args.batch)],
               lambda bl: int(lengths.max()), "naive global-max")
        rs = np.random.RandomState(1)
        perm = rs.permutation(args.docs)
        yield ([perm[i:i + args.batch]
                for i in range(0, args.docs, args.batch)],
               lambda bl: int(bl.max()), "per-batch max")
        for name, ladder in LADDERS.items():
            sampler = BucketedBatchSampler(
                ds, batch_size=args.batch, buckets=ladder,
                length_fn=lambda i: int(lengths[i]), shuffle=True)
            yield ([np.asarray(b) for b in sampler],
                   lambda bl, _l=ladder: bucket_for(int(bl.max()), _l),
                   f"bucketed {name} {tuple(ladder)}")

    results = []
    for batches, pad_fn, label in strategies():
        if args.analytic_only:
            results.append(analytic(lengths, batches, pad_fn, label))
        else:
            results.append(run_strategy(docs, lengths, batches, pad_fn,
                                        args.batch, args.steps_cap,
                                        label))
        print(json.dumps(results[-1]), flush=True)

    print(json.dumps({"all": results}))


if __name__ == "__main__":
    main()
