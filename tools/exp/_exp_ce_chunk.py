"""Fused-CE chunk-size sweep on hardware (round-3 MFU push).

The chunked head+CE scan is ~18% of the GPT-2 345M step (BASELINE.md
round-3 breakdown).  Chunk size trades scan iterations (per-iteration
dW-accumulate traffic over the [H, V] head grad) against live logits
HBM ([B, chunk, V] f32).  Sweeps chunk at b8 s1024 and prints tokens/s
per setting; also the first data for the dynamic_slice scan rewrite
(chunks sliced from [B, S, H] in-body instead of a pre-transposed scan
input).

Usage: python tools/exp/_exp_ce_chunk.py [--steps 20]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--chunks", default="128,256,512,1024")
    args = ap.parse_args()

    import jax
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep

    on_tpu = jax.default_backend() != "cpu"
    batch, seq, cfg = (8, 1024, "gpt2-medium") if on_tpu else \
        (2, 128, "tiny")
    rng = np.random.RandomState(0)
    vocab = 50304 if cfg != "tiny" else 128
    ids = rng.randint(0, vocab, (batch, seq + 1)).astype(np.int32)
    x, y = ids[:, :-1], ids[:, 1:]

    out = {"backend": jax.default_backend(), "batch": batch, "seq": seq}
    for chunk in [int(c) for c in args.chunks.split(",")]:
        paddle.seed(0)
        model = GPTModel.from_config(cfg, dropout=0.1, fused_loss=True,
                                     fused_loss_chunk=chunk)
        if on_tpu:
            model.to(dtype="bfloat16")
        opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                              parameters=model.parameters())
        step = TrainStep(model, opt, loss_fn=None)
        loss = step.step([x, y])
        loss.numpy()  # compile + sync
        t0 = time.perf_counter()
        for _ in range(args.steps):
            loss = step.step([x, y])
        loss.numpy()
        dt = time.perf_counter() - t0
        rate = round(batch * seq * args.steps / dt, 1)
        out[f"chunk{chunk}"] = {"tokens_per_s": rate,
                                "loss": round(float(loss.numpy()), 4)}
        print(json.dumps({f"chunk{chunk}": out[f"chunk{chunk}"]}),
              flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
