#!/bin/bash
# Run the pending TPU measurement backlog the moment the tunnel recovers.
# ONE process may use the TPU at a time; steps run strictly sequentially
# and each is subprocess-isolated so a hang cannot poison the next.
#
# Round-3 history: the original backlog (bench, 1.3B, prof, gen, ragged,
# packed) ran at the first recovery window — raw outputs archived in
# tools/exp/results_r3/.  This file now lists the REMAINING legs queued
# when the tunnel died again mid-round.
# Usage:  bash tools/exp/tpu_recovery_runbook.sh [outdir]
set -u
OUT=${1:-/tmp/tpu_r3e}
mkdir -p "$OUT"
cd "$(dirname "$0")/../.."

run() {  # run NAME TIMEOUT CMD...
  local name=$1 t=$2; shift 2
  echo "=== $name (timeout ${t}s)"
  timeout "$t" "$@" > "$OUT/$name.json" 2> "$OUT/$name.err"
  echo "rc=$? -> $OUT/$name.json"
}

# 0) probe (cheap, bounded).  NOTE: the first ~15 min after recovery
#    serve degraded throughput (BASELINE.md round 3) — treat the first
#    timing pass as suspect and re-run anything anomalous.
run probe 240 python -c "import jax; print(jax.devices())"
grep -q TPU "$OUT/probe.json" || { echo "TPU not reachable; abort"; exit 1; }

# 1) headline re-capture (hardened bench: subprocess-isolated, retries)
run bench 3600 python bench.py

# 2) device-resident BERT recheck (bench_bert was made device-resident
#    after 436-705 samples/s feed jitter; expect ~1 stable number now)
run bert 1800 python bench.py --only bert

# 3) fused flat-slab optimizer A/B on GPT-2 345M b8
#    (PADDLE_TPU_FUSE_OPT=1; exact-equivalence tested on CPU)
run fuseopt_off 1200 python tools/exp/_exp_perf.py 8 8
# env(1) scopes the flag to this leg only (VAR=x before a bash FUNCTION
# would persist after the call and contaminate the 13b legs)
run fuseopt_on 1200 env PADDLE_TPU_FUSE_OPT=1 python tools/exp/_exp_perf.py 8 8

# 4) 1.3B scan-over-layers legs (CPU rehearsal: compile 212-460s -> 18.6s;
#    compare on-device compile + tok/s vs unrolled 200s / 13,860)
run 13b_scan_compile 2400 python tools/exp/_exp_13b.py --scan --compile-only --batch 1 --seq 1024
run 13b_scan_b2 2400 python tools/exp/_exp_13b.py --scan --batch 2 --seq 1024 --steps 10

# 5) long-context s4096 round-3 leg (round-2 recorded 24,472 tok/s b3)
run long 1800 python tools/exp/_exp_long.py

echo "=== backlog complete; fold results into BASELINE.md and archive"
echo "=== raw outputs under tools/exp/results_r3/"
