#!/bin/bash
# Run the pending TPU measurement backlog the moment the tunnel recovers.
# ONE process may use the TPU at a time; steps run strictly sequentially
# and each is subprocess-isolated so a hang cannot poison the next.
#
# Round-5 note: bench.py now runs a ~5s tiny-model canary before the
# 345M leg — a wedged tunnel aborts in minutes and a live canary's
# tok/s is published even if the 345M leg dies.  The backlog below is
# carried from round 4 (the tunnel never came up that round); the
# fuse-opt A/B gained a mixed-dtype bitwise-equivalence audit
# (tests/test_optimizer.py::test_mixed_dtype_params_group_separately)
# so PADDLE_TPU_FUSE_OPT can default on the moment the A/B wins.
#
# Round-4 backlog (VERDICT r3 tasks 1-3): driver-provable bench capture,
# BERT device-resident re-measure (3 runs — explain or erase the
# 704.9 -> 561.5 drop), 1.3B b1 clean-window re-measure (3 runs — the
# round-3 number was transport-poisoned), fused-optimizer A/B, 1.3B
# scan-over-layers legs, re-profile under the fused optimizer, long
# context.  Raw round-3 outputs live in tools/exp/results_r3/.
# Usage:  bash tools/exp/tpu_recovery_runbook.sh [outdir]
set -u
OUT=${1:-/tmp/tpu_r4}
mkdir -p "$OUT"
cd "$(dirname "$0")/../.."

run() {  # run NAME TIMEOUT CMD...
  local name=$1 t=$2; shift 2
  echo "=== $name (timeout ${t}s)"
  timeout "$t" "$@" > "$OUT/$name.json" 2> "$OUT/$name.err"
  echo "rc=$? -> $OUT/$name.json"
}

# 0) reachability probe (cheap, bounded)
run probe 240 python -c "import jax; print(jax.devices())"
grep -q TPU "$OUT/probe.json" || { echo "TPU not reachable; abort"; exit 1; }

# 0b) degraded-window gate: the ~15 min after a tunnel recovery serve
#     ~13x-slow throughput (BASELINE.md forensics).  Wait until H2D
#     bandwidth clears 100 MB/s before taking ANY number (max ~25 min).
for i in $(seq 1 10); do
  timeout 300 python - > "$OUT/h2d_$i.txt" 2>&1 <<'EOF'
import time, numpy as np, jax
buf = np.zeros((10_000_000,), np.float32)
jax.device_put(buf).block_until_ready()          # warm the path
bws = []
for _ in range(2):
    t0 = time.perf_counter()
    jax.device_put(buf).block_until_ready()
    bws.append(buf.nbytes / (time.perf_counter() - t0) / 1e6)
print(f"h2d_MBps={max(bws):.1f}")
print("HEALTHY" if max(bws) >= 100 else "DEGRADED")
EOF
  grep -q HEALTHY "$OUT/h2d_$i.txt" && { echo "H2D healthy (pass $i)"; break; }
  echo "degraded window (pass $i): $(cat "$OUT/h2d_$i.txt")"; sleep 120
  if [ "$i" -eq 10 ]; then
    # the entire point of this gate is that numbers taken in the
    # degraded window are worthless (round-3 1,441 tok/s artifact)
    touch "$OUT/DEGRADED_GATE_FAILED"
    echo "H2D still degraded after 10 passes; ABORT (re-run later)"
    exit 1
  fi
done

# 1) headline capture, exactly as the driver runs it (the bench's own
#    budget/probe logic is the contract under test)
run bench 1000 env BENCH_BUDGET_S=900 python bench.py

# 2) BERT device-resident, 3 runs (variance bounds for BASELINE.md)
run bert_1 700 python bench.py --only bert
run bert_2 700 python bench.py --only bert
run bert_3 700 python bench.py --only bert

# 3) 1.3B b1 clean-window re-measure, 3 runs (round-3 1,441 tok/s was
#    taken inside the degraded window; b2/b4 measured 13.8k)
run 13b_b1_1 2400 python tools/exp/_exp_13b.py --batch 1 --seq 1024 --steps 10
run 13b_b1_2 1200 python tools/exp/_exp_13b.py --batch 1 --seq 1024 --steps 10
run 13b_b1_3 1200 python tools/exp/_exp_13b.py --batch 1 --seq 1024 --steps 10

# 4) fused flat-slab optimizer A/B on GPT-2 345M b8
#    (PADDLE_TPU_FUSE_OPT=1; exact-equivalence tested on CPU).
#    env(1) scopes the flag to one leg only.
run fuseopt_off 1200 python tools/exp/_exp_perf.py 8 8
run fuseopt_on 1200 env PADDLE_TPU_FUSE_OPT=1 python tools/exp/_exp_perf.py 8 8

# 5) re-profile under the fused optimizer: the round-3 trace put 52.4%
#    of step time in elementwise/other fusions — show the bucket moving
run prof_fused 1800 env PADDLE_TPU_FUSE_OPT=1 python tools/exp/_exp_prof.py --steps 20

# 6) 1.3B scan-over-layers legs (CPU rehearsal: compile 212-460s -> 18.6s;
#    compare on-device compile + tok/s vs unrolled 200s / 13,860)
run 13b_scan_compile 2400 python tools/exp/_exp_13b.py --scan --compile-only --batch 1 --seq 1024
run 13b_scan_b2 2400 python tools/exp/_exp_13b.py --scan --batch 2 --seq 1024 --steps 10

# 7) long-context s4096 (round-2 recorded 24,472 tok/s b3)
run long 1800 python tools/exp/_exp_long.py

# 7b) roofline calibration (VERDICT r4 weak-#5/next-#8): compare the
#     dryrun [dryrun:cost] flops/HBM terms against the XPlane trace
#     from step 5 for the same single-chip step; record the scale
#     factor so the MULTICHIP cost lines can say "calibrated vs v5e
#     single-chip (factor X)" instead of "uncalibrated roofline".
run roofline_calib 900 python - <<'EOF'
import json
import numpy as np, jax, paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.models import GPTModel
from paddle_tpu.parallel.train_step import TrainStep
paddle.seed(0)
model = GPTModel.from_config("gpt2-medium", fused_loss=True)
model.to(dtype="bfloat16")
step = TrainStep(model, optimizer.AdamW(
    learning_rate=1e-4, parameters=model.parameters()), loss_fn=None)
rng = np.random.RandomState(0)
ids = rng.randint(0, 50304, (8, 1025)).astype(np.int32)
x, y = ids[:, :-1], ids[:, 1:]
_, _, compiled = step.aot_compile([x, y])
cost = compiled.cost_analysis() or {}
if isinstance(cost, (list, tuple)):
    cost = cost[0] if cost else {}
import time
loss = step.step([x, y]); loss.numpy()
t0 = time.perf_counter()
for _ in range(10):
    loss = step.step([x, y])
loss.numpy()
dt = (time.perf_counter() - t0) / 10
flops = float(cost.get("flops", 0.0))
hbm = float(cost.get("bytes accessed", 0.0))
V5E_FLOPS, V5E_HBM = 197e12, 819e9  # bf16 peak, same anchors as __graft_entry__._V5E_BF16_FLOPS
roofline_ms = 1e3 * max(flops / V5E_FLOPS, hbm / V5E_HBM)
print(json.dumps({
    "measured_step_ms": round(dt * 1e3, 2),
    "roofline_est_ms": round(roofline_ms, 2),
    "calibration_factor": round(dt * 1e3 / max(roofline_ms, 1e-9), 3),
    "flops": flops, "hbm_bytes": hbm}))
EOF

# 7c) speculative decode (round 5): fused vs speculative latency on
#     the 345M through the tunnel; untrained-weights caveat — real
#     accept rates need a trained checkpoint, so record forwards too
run spec_decode 1200 python - <<'PYEOF'
import json, time
import numpy as np, paddle_tpu as paddle
from paddle_tpu.models import GPTModel
paddle.seed(0)
model = GPTModel.from_config("gpt2-medium", dropout=0.0)
model.to(dtype="bfloat16")
model.eval()
ids = paddle.to_tensor(np.tile(
    np.array([11, 22, 33, 44], np.int32), 8)[None, :])
res = {}
for mode in ("fused", "speculative"):
    out = model.generate(ids, max_new_tokens=64, compiled=mode)
    out.numpy()
    t0 = time.perf_counter()
    for _ in range(3):
        out = model.generate(ids, max_new_tokens=64, compiled=mode)
    out.numpy()
    res[mode] = round((time.perf_counter() - t0) / 3 * 1e3, 1)
res["spec_forwards"] = model.last_spec_forwards
print(json.dumps(res))
PYEOF

# 8) py_func host-callback smoke ON TPU: pure_callback crosses the axon
#    tunnel via XLA host callbacks — prove the round-4 op works there
run pyfunc_smoke 300 python - <<'EOF'
import numpy as np, paddle_tpu as paddle
x = paddle.to_tensor(np.linspace(-1, 1, 8).astype("float32"),
                     stop_gradient=False)
y = paddle.static.py_func(lambda a: np.tanh(a), x, paddle.zeros([8]),
                          backward_func=lambda a, b, d: [d * (1 - b * b)])
paddle.sum(y).backward()
import json
print(json.dumps({"pyfunc_fwd_ok": bool(np.allclose(
    y.numpy(), np.tanh(np.linspace(-1, 1, 8)), atol=1e-5)),
    "grad_finite": bool(np.isfinite(x.grad.numpy()).all())}))
EOF

echo "=== backlog complete; fold results into BASELINE.md and archive"
echo "=== under tools/exp/results_r4/ (cp -r $OUT tools/exp/results_r4)"
