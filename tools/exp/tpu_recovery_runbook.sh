#!/bin/bash
# Run the round-3 TPU measurement backlog the moment the tunnel recovers.
# ONE process may use the TPU at a time; steps run strictly sequentially
# and each is subprocess-isolated so a hang cannot poison the next.
# Usage:  bash tools/exp/tpu_recovery_runbook.sh [outdir]
set -u
OUT=${1:-/tmp/tpu_r3}
mkdir -p "$OUT"
cd "$(dirname "$0")/../.."

run() {  # run NAME TIMEOUT CMD...
  local name=$1 t=$2; shift 2
  echo "=== $name (timeout ${t}s)"
  timeout "$t" "$@" > "$OUT/$name.json" 2> "$OUT/$name.err"
  echo "rc=$? -> $OUT/$name.json"
}

# 0) probe (cheap, bounded)
run probe 240 python -c "import jax; print(jax.devices())"
grep -q TPU "$OUT/probe.json" || { echo "TPU not reachable; abort"; exit 1; }

# 1) the driver-visible headline: all three models via hardened bench.py
run bench 3600 python bench.py

# 2) GPT-3 1.3B single-chip: compile rehearsal on device, then measure.
#    (CPU rehearsal already bounded XLA time; see BASELINE.md round 3.)
run 13b_compile 2400 python tools/exp/_exp_13b.py --compile-only --batch 1 --seq 1024
run 13b_b1 2400 python tools/exp/_exp_13b.py --batch 1 --seq 1024 --steps 10
run 13b_b2 2400 python tools/exp/_exp_13b.py --batch 2 --seq 1024 --steps 10
run 13b_b4 2400 python tools/exp/_exp_13b.py --batch 4 --seq 1024 --steps 10

# 3) profiler trace for the MFU breakdown (VERDICT round-2 #3)
run prof 1800 python tools/exp/_exp_prof.py

# 4) compiled generation prefill+decode (VERDICT round-2 #8)
run gen 1800 python tools/exp/_exp_gen_tpu.py

# 5) ragged wall-clock leg on hardware (BASELINE round-3 table)
run ragged 2400 python tools/exp/_exp_ragged.py --docs 512 --batch 8 --steps-cap 24

# 6) packed vs padded pretraining throughput (flash segment ids)
run packed 2400 python tools/exp/_exp_packed.py --budget 4096 --steps 12

echo "=== backlog complete; fold results into BASELINE.md"
