"""GPT-3 1.3B single-chip fit recipe (BASELINE config 5, single-chip leg).

The recipe (VERDICT round-2 #2): bf16 params + bf16 optimizer moments
(`AdamW(multi_precision=False)`) + per-block dots-policy remat + fused
(sequence-chunked) head+CE + donated buffers.  Expected HBM at b1 s1024:
  params 2.6GB + moments 5.2GB -> 2.6GB (bf16) + grads 2.6GB (donated)
  + remat activations ~0.1GB  ==>  ~8GB, inside a 16GB v5e chip.

Two modes:
  --compile-only   AOT lower+compile and print XLA compile time and the
                   compiled memory analysis (works on the CPU backend;
                   bounds XLA time BEFORE touching the tunnel — a killed
                   1.3B tunnel compile is what took the chip down in
                   round 2).
  (default)        run `--steps` training steps and print tokens/s.

Usage:
  PADDLE_TPU_PLATFORM=cpu python tools/exp/_exp_13b.py --compile-only \
      --batch 1 --seq 256          # CPU rehearsal (small seq)
  python tools/exp/_exp_13b.py --batch 1 --seq 1024 --steps 10   # on TPU
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))

import numpy as np


def build(args):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep

    paddle.seed(0)
    model = GPTModel.from_config(
        "gpt3-1.3b", dropout=args.dropout, fused_loss=True,
        scan_layers=args.scan,
        use_recompute=not args.no_remat,
        recompute_policy=(None if args.policy == "full" else args.policy)
        if not args.no_remat else None)
    model.to(dtype="bfloat16")
    opt = optimizer.AdamW(
        learning_rate=1e-4, weight_decay=0.01,
        parameters=model.parameters(),
        multi_precision=not args.bf16_moments)
    step = TrainStep(model, opt, loss_fn=None, donate=True)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50304, (args.batch, args.seq + 1)).astype(np.int32)
    return step, ids[:, :-1], ids[:, 1:]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--dropout", type=float, default=0.0)
    ap.add_argument("--compile-only", action="store_true")
    ap.add_argument("--scan", action="store_true",
                    help="scan-over-layers form (one compiled block "
                         "body; see GPTScanBlocks)")
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument("--policy", default="dots",
                    choices=["full", "dots", "nothing", "everything"])
    ap.add_argument("--bf16-moments", action="store_true", default=True)
    ap.add_argument("--f32-moments", dest="bf16_moments",
                    action="store_false")
    args = ap.parse_args()

    import jax
    step, x, y = build(args)
    out = {"config": vars(args), "backend": jax.default_backend()}

    if args.compile_only:
        t_lower, t_compile, compiled = step.aot_compile([x, y])
        out["lower_s"] = round(t_lower, 1)
        out["compile_s"] = round(t_compile, 1)
        try:
            ma = compiled.memory_analysis()
            out["memory_analysis"] = {
                "argument_size_gb": round(
                    ma.argument_size_in_bytes / 2 ** 30, 2),
                "output_size_gb": round(
                    ma.output_size_in_bytes / 2 ** 30, 2),
                "temp_size_gb": round(
                    ma.temp_size_in_bytes / 2 ** 30, 2),
                "peak_gb_est": round(
                    (max(ma.argument_size_in_bytes,
                         ma.output_size_in_bytes)
                     + ma.temp_size_in_bytes) / 2 ** 30, 2),
            }
        except Exception as e:  # backend without memory analysis
            out["memory_analysis"] = f"unavailable: {e!r}"
        print(json.dumps(out), flush=True)
        return

    t0 = time.perf_counter()
    loss = step.step([x, y])
    loss.numpy()
    out["first_step_s"] = round(time.perf_counter() - t0, 1)
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = step.step([x, y])
    lv = float(loss.numpy())
    dt = time.perf_counter() - t0
    out["loss"] = round(lv, 3)
    out["tokens_per_s"] = round(args.batch * args.seq * args.steps / dt, 1)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
