"""Measure DataLoader-fed vs device-resident training throughput on the
real chip (VERDICT round-1 item #1).

Pipeline under test: process workers (shared memory) -> DeviceLoader async
H2D double buffer -> TrainStep.  Also measures the raw H2D bandwidth bound
so pipeline efficiency = fed_rate / min(compute_rate, transfer_bound) is
explicit.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))
import numpy as np


def timed(fn, n, sync):
    fn()  # warm
    sync()
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    sync()
    return (time.perf_counter() - t0) / n


def main():
    import jax
    import paddle_tpu as paddle
    from paddle_tpu import optimizer, nn, io
    from paddle_tpu.parallel.train_step import TrainStep

    on_tpu = jax.default_backend() != "cpu"
    print("backend:", jax.default_backend())
    out = {}

    # ---- raw H2D bandwidth bound --------------------------------------
    arr = np.random.rand(64, 3, 224, 224).astype(np.float32)  # 38.5 MB
    dev = jax.devices()[0]

    def put():
        jax.device_put(arr, dev).block_until_ready()

    dt = timed(put, 5, lambda: None)
    out["h2d_MBps"] = round(arr.nbytes / dt / 1e6, 1)
    out["h2d_sec_per_resnet_batch"] = round(dt, 4)

    # ---- ResNet-50 -----------------------------------------------------
    from paddle_tpu.vision.models import resnet50
    paddle.seed(0)
    batch = 64
    model = resnet50(num_classes=1000)
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                             parameters=model.parameters())
    step = TrainStep(model, opt, loss_fn=nn.CrossEntropyLoss(),
                     amp_level="O1")

    rng = np.random.RandomState(0)
    x_host = rng.rand(batch, 3, 224, 224).astype(np.float32)
    y_host = rng.randint(0, 1000, (batch,)).astype(np.int64)

    # device-resident: same arrays already on device
    x_dev = jax.device_put(x_host, step._data_sharding(x_host.shape))
    y_dev = jax.device_put(y_host, step._data_sharding(y_host.shape))
    loss = step.step([x_dev], [y_dev]); loss.numpy()  # compile

    n = 20 if on_tpu else 3
    dt = timed(lambda: step.step([x_dev], [y_dev]), n,
               lambda: step.params["fc.weight"].block_until_ready())
    out["resnet_device_resident_sps"] = round(batch / dt, 1)

    # sync feed: host numpy each step (round-1's 27/s path)
    dt = timed(lambda: step.step([x_host], [y_host]), max(3, n // 4),
               lambda: step.params["fc.weight"].block_until_ready())
    out["resnet_sync_feed_sps"] = round(batch / dt, 1)

    # full pipeline: mp DataLoader + DeviceLoader prefetch
    class SynthImages(io.Dataset):
        def __init__(self, nitems):
            self.n = nitems

        def __getitem__(self, i):
            rs = np.random.RandomState(i)
            return (rs.rand(3, 224, 224).astype(np.float32),
                    np.asarray(rs.randint(1000), np.int64))

        def __len__(self):
            return self.n

    steps_total = n
    loader = io.DataLoader(SynthImages(batch * steps_total),
                           batch_size=batch, num_workers=8,
                           prefetch_factor=2, drop_last=True)
    devloader = io.DeviceLoader(loader, buffer_size=2,
                                sharding_fn=step._data_sharding,
                                wrap=False)
    # warm one epoch-start (workers spin up)
    t0 = time.perf_counter()
    seen = 0
    for bx, by in devloader:
        loss = step.step([bx], [by])
        seen += batch
    loss.numpy()
    dt_all = time.perf_counter() - t0
    out["resnet_pipelined_fed_sps"] = round(seen / dt_all, 1)
    out["resnet_fed_vs_resident"] = round(
        out["resnet_pipelined_fed_sps"] /
        out["resnet_device_resident_sps"], 3)
    bound = min(out["resnet_device_resident_sps"],
                out["h2d_MBps"] * 1e6 / (x_host.nbytes / batch))
    out["resnet_fed_vs_bound"] = round(
        out["resnet_pipelined_fed_sps"] / bound, 3)

    # ---- GPT-2 (fed) ---------------------------------------------------
    from paddle_tpu.models import GPTModel
    if on_tpu:
        gbatch, gseq, cfg, gsteps = 8, 1024, "gpt2-medium", 20
    else:
        gbatch, gseq, cfg, gsteps = 2, 128, "tiny", 3
    paddle.seed(0)
    gmodel = GPTModel.from_config(cfg, dropout=0.1, fused_loss=True)
    if on_tpu:
        gmodel.to(dtype="bfloat16")
    gopt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                           parameters=gmodel.parameters())
    gstep = TrainStep(gmodel, gopt, loss_fn=None)
    vocab = 50304 if cfg != "tiny" else 128

    class SynthTokens(io.Dataset):
        def __init__(self, nitems):
            self.n = nitems

        def __getitem__(self, i):
            rs = np.random.RandomState(i)
            ids = rs.randint(0, vocab, (gseq + 1,)).astype(np.int32)
            return ids[:-1], ids[1:]

        def __len__(self):
            return self.n

    ids = np.random.RandomState(0).randint(
        0, vocab, (gbatch, gseq + 1)).astype(np.int32)
    gx, gy = ids[:, :-1], ids[:, 1:]
    gx_d = jax.device_put(gx, gstep._data_sharding(gx.shape))
    gy_d = jax.device_put(gy, gstep._data_sharding(gy.shape))
    l = gstep.step([gx_d, gy_d]); l.numpy()
    dt = timed(lambda: gstep.step([gx_d, gy_d]), gsteps, lambda: None)
    out["gpt2_device_resident_tps"] = round(gbatch * gseq / dt, 1)

    gloader = io.DataLoader(SynthTokens(gbatch * gsteps),
                            batch_size=gbatch, num_workers=4,
                            drop_last=True)
    gdev = io.DeviceLoader(gloader, buffer_size=2,
                           sharding_fn=gstep._data_sharding, wrap=False)
    t0 = time.perf_counter()
    tok = 0
    for bx, by in gdev:
        l = gstep.step([bx, by])
        tok += gbatch * gseq
    l.numpy()
    out["gpt2_pipelined_fed_tps"] = round(tok / (time.perf_counter() - t0), 1)
    out["gpt2_fed_vs_resident"] = round(
        out["gpt2_pipelined_fed_tps"] / out["gpt2_device_resident_tps"], 3)

    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
