"""Validate the new default blocks; try batch 4 and seq 8192."""
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))
import numpy as np

def run(seq, batch, steps=6):
    import paddle_tpu as paddle
    from paddle_tpu import optimizer
    from paddle_tpu.models import GPTModel
    from paddle_tpu.parallel.train_step import TrainStep
    paddle.seed(0)
    model = GPTModel.from_config("gpt2-medium", dropout=0.1,
                                 fused_loss=True, max_position=seq)
    model.to(dtype="bfloat16")
    opt = optimizer.AdamW(learning_rate=1e-4, weight_decay=0.01,
                          parameters=model.parameters())
    step = TrainStep(model, opt, loss_fn=None)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50304, (batch, seq + 1)).astype(np.int32)
    x, y = ids[:, :-1], ids[:, 1:]
    loss = step.step([x, y]); loss.numpy()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step.step([x, y])
    loss.numpy()
    dt = time.perf_counter() - t0
    print(f"seq={seq} batch={batch}: {batch*seq*steps/dt:.0f} tok/s",
          flush=True)

if __name__ == "__main__":
    for seq, batch in [(4096, 2), (4096, 4), (8192, 1), (8192, 2)]:
        try:
            run(seq, batch)
        except Exception as e:
            print(f"seq={seq} b={batch}: FAILED {type(e).__name__}",
                  flush=True)
