"""mp=2 step-time microbench on the 8-device CPU mesh (TP remat check)."""
import os
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()
import time
import sys
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), "..", ".."))
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import optimizer
from paddle_tpu.models import GPTModel, GPTPretrainingCriterion
from paddle_tpu.parallel.train_step import TrainStep

mesh = dist.build_mesh(dp=4, mp=2, devices=jax.devices()[:8])
dist.set_mesh(mesh)
paddle.seed(0)
model = GPTModel(num_layers=4, hidden_size=256, num_heads=8,
                 vocab_size=1024, max_position=256, dropout=0.0,
                 use_mp=True)
opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
step = TrainStep(model, opt, loss_fn=GPTPretrainingCriterion(),
                 donate=False)
rng = np.random.RandomState(0)
ids = rng.randint(0, 1024, (8, 129)).astype(np.int64)
loss = step.step([ids[:, :-1]], [ids[:, 1:]]); loss.numpy()
t0 = time.perf_counter()
N = 20
for _ in range(N):
    loss = step.step([ids[:, :-1]], [ids[:, 1:]])
loss.numpy()
print(f"mp=2 dp=4 step time: {(time.perf_counter()-t0)/N*1000:.1f} ms  "
      f"loss={float(loss.numpy()):.4f}")
