#!/usr/bin/env python
"""CI test sharding (reference parity: tools/parallel_UT_rule.py — the
reference partitions its 916-file suite into parallel CI buckets).

Usage:  python tools/split_tests.py NUM_SHARDS SHARD_INDEX
Prints the test files for that shard, balanced by historical duration
when tools/test_durations.json exists (write it with
`pytest --store-durations` style timing or the helper below), else by
file size as a proxy.

    pytest $(python tools/split_tests.py 4 0)
"""
import json
import os
import sys


def main():
    n = int(sys.argv[1])
    idx = int(sys.argv[2])
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests_dir = os.path.join(root, "tests")
    files = sorted(f for f in os.listdir(tests_dir)
                   if f.startswith("test_") and f.endswith(".py"))
    durations_path = os.path.join(root, "tools", "test_durations.json")
    if os.path.exists(durations_path):
        with open(durations_path) as fh:
            durations = json.load(fh)
        weight = {f: float(durations.get(f, 1.0)) for f in files}
    else:
        weight = {f: os.path.getsize(os.path.join(tests_dir, f))
                  for f in files}
    # longest-processing-time greedy balance
    shards = [[] for _ in range(n)]
    loads = [0.0] * n
    for f in sorted(files, key=lambda f: -weight[f]):
        k = loads.index(min(loads))
        shards[k].append(f)
        loads[k] += weight[f]
    print(" ".join(os.path.join("tests", f) for f in sorted(shards[idx])))


if __name__ == "__main__":
    main()
