#!/usr/bin/env python
"""Record per-file test durations for split_tests.py.

    python tools/record_durations.py  # runs the fast suite, writes
                                      # tools/test_durations.json
"""
import json
import os
import re
import subprocess
import sys
import time


def main():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests_dir = os.path.join(root, "tests")
    files = sorted(f for f in os.listdir(tests_dir)
                   if f.startswith("test_") and f.endswith(".py"))
    out = {}
    for f in files:
        t0 = time.perf_counter()
        r = subprocess.run(
            [sys.executable, "-m", "pytest",
             os.path.join("tests", f), "-q", "-m", "not slow"],
            cwd=root, capture_output=True, text=True)
        out[f] = round(time.perf_counter() - t0, 2)
        status = "ok" if r.returncode in (0, 5) else "FAIL"
        print(f"{f}: {out[f]}s {status}", flush=True)
    with open(os.path.join(root, "tools", "test_durations.json"),
              "w") as fh:
        json.dump(out, fh, indent=1, sort_keys=True)


if __name__ == "__main__":
    main()
