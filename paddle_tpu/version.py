"""paddle.version (reference: generated python/paddle/version.py)."""
full_version = "2.0.0-tpu"
major = "2"
minor = "0"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"
with_mkl = "OFF"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")


def mkl():
    return with_mkl
