"""Long-tail optimizers and parameter-averaging utilities.

Reference parity: ``python/paddle/fluid/optimizer.py`` hosts
ExponentialMovingAverage / ModelAverage / LookaheadOptimizer and the
DecayedAdagrad / Ftrl / Dpsgd update rules (kernels in
``operators/optimizers/``).  The update rules follow this package's pure
``_update`` protocol; the averaging utilities operate eagerly on the
Layer's parameter Tensors (the reference manipulates scope vars the same
way, just through program ops).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from . import Optimizer


class DecayedAdagrad(Optimizer):
    """reference: operators/optimizers/decayed_adagrad_op.cc —
    m = decay*m + (1-decay)*g^2."""

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip)
        self._decay = decay
        self._epsilon = epsilon

    def _init_state(self, param):
        return {"moment": jnp.zeros_like(param._data if isinstance(
            param, Tensor) else param)}

    def _update(self, param, grad, state, lr):
        if self._weight_decay:
            grad = grad + self._weight_decay * param
        m = self._decay * state["moment"] + \
            (1.0 - self._decay) * jnp.square(grad)
        new_param = param - lr * grad / (jnp.sqrt(m) + self._epsilon)
        return new_param, {"moment": m}


class Ftrl(Optimizer):
    """FTRL-proximal (reference: operators/optimizers/ftrl_op.cc with
    lr_power=-0.5)."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _init_state(self, param):
        z = jnp.zeros_like(param._data if isinstance(param, Tensor)
                           else param)
        return {"squared": z, "linear": z}

    def _update(self, param, grad, state, lr):
        sq, lin = state["squared"], state["linear"]
        new_sq = sq + jnp.square(grad)
        p = -self._lr_power
        sigma = (new_sq ** p - sq ** p) / lr
        new_lin = lin + grad - sigma * param
        pre = -(new_lin - jnp.sign(new_lin) * self._l1) / (
            new_sq ** p / lr + self._l2)
        new_param = jnp.where(jnp.abs(new_lin) > self._l1, pre,
                              jnp.zeros_like(param))
        return new_param, {"squared": new_sq, "linear": new_lin}


class Dpsgd(Optimizer):
    """Differentially-private SGD (reference:
    operators/optimizers/dpsgd_op.cc): per-update clip to ``clip`` then
    add N(0, sigma*clip) noise.  Noise is drawn from a counter-based key
    so the rule stays a pure function of (param, grad, state)."""

    def __init__(self, learning_rate, clip=10.0, batch_size=16.0,
                 sigma=1.0, parameters=None, seed=0, name=None):
        super().__init__(learning_rate, parameters, None, None)
        self._clip = clip
        self._batch = batch_size
        self._sigma = sigma
        self._seed = seed

    def _init_state(self, param):
        return {"t": jnp.zeros((), jnp.int32)}

    def _update(self, param, grad, state, lr):
        t = state["t"]
        norm = jnp.sqrt(jnp.sum(jnp.square(grad)))
        scaled = grad * (self._clip / jnp.maximum(norm, self._clip))
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.key(self._seed), t),
            param.size)
        noise = jax.random.normal(key, param.shape, param.dtype) * (
            self._sigma * self._clip / self._batch)
        new_param = param - lr * (scaled + noise)
        return new_param, {"t": t + 1}


class ExponentialMovingAverage:
    """reference: fluid/optimizer.py ExponentialMovingAverage —
    ``update()`` after each optimizer step; ``apply()`` as a context
    manager swaps EMA weights in (bias-corrected), restoring on exit."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._step = 0
        self._params = {}

    def _register(self, layer_or_params):
        params = (list(layer_or_params.parameters())
                  if hasattr(layer_or_params, "parameters")
                  else list(layer_or_params))
        for i, p in enumerate(params):
            self._params[i] = p
            if i not in self._shadow:
                # Zero-init to match the reference (_create_ema_vars inits the
                # EMA var to 0.0), which is what justifies apply()'s division
                # by the bias-correction factor 1 - decay^t.
                self._shadow[i] = jnp.zeros_like(p._data)

    def update(self, layer_or_params=None):
        if layer_or_params is not None or not self._params:
            if layer_or_params is None:
                raise ValueError(
                    "ExponentialMovingAverage.update: pass the Layer (or "
                    "parameter list) on first use")
            self._register(layer_or_params)
        self._step += 1
        d = self._decay
        for i, p in self._params.items():
            self._shadow[i] = d * self._shadow[i] + (1.0 - d) * p._data

    def apply(self, executor=None, need_restore=True):
        ema = self

        class _Guard:
            def __enter__(self):
                bias = 1.0 - ema._decay ** max(ema._step, 1)
                for i, p in ema._params.items():
                    ema._backup[i] = p._data
                    p._data = ema._shadow[i] / bias
                return ema

            def __exit__(self, *exc):
                if need_restore:
                    ema.restore()
                return False

        return _Guard()

    def restore(self, executor=None):
        for i, p in self._params.items():
            if i in self._backup:
                p._data = self._backup.pop(i)


class ModelAverage:
    """reference: fluid/optimizer.py ModelAverage — accumulate parameter
    sums over a sliding window; ``apply()`` swaps in the window mean."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, parameters=None, name=None):
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._params = list(parameters or [])
        self._sum = [jnp.zeros_like(p._data) for p in self._params]
        self._count = 0
        self._backup = {}

    def update(self):
        window = max(self._min_w,
                     min(self._max_w, int(self._count * self._rate) or 1))
        if self._count >= window:
            self._sum = [jnp.zeros_like(p._data) for p in self._params]
            self._count = 0
        for i, p in enumerate(self._params):
            self._sum[i] = self._sum[i] + p._data
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        ma = self

        class _Guard:
            def __enter__(self):
                n = max(ma._count, 1)
                for i, p in enumerate(ma._params):
                    ma._backup[i] = p._data
                    p._data = ma._sum[i] / n
                return ma

            def __exit__(self, *exc):
                if need_restore:
                    ma.restore()
                return False

        return _Guard()

    def restore(self, executor=None):
        for i, p in enumerate(self._params):
            if i in self._backup:
                p._data = self._backup.pop(i)


class LookaheadOptimizer:
    """reference: fluid/optimizer.py LookaheadOptimizer — fast weights
    step with the inner optimizer; every k steps the slow weights move
    alpha toward the fast ones and the fast weights reset to them."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha must be in [0, 1], got {alpha}")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = int(k)
        self._slow = None
        self._steps = 0

    def _params(self):
        return self.inner_optimizer._parameter_list

    def step(self):
        self.inner_optimizer.step()
        if self._slow is None:
            self._slow = [jnp.array(p._data) for p in self._params()]
        self._steps += 1
        if self._steps % self.k == 0:
            for i, p in enumerate(self._params()):
                self._slow[i] = self._slow[i] + self.alpha * (
                    p._data - self._slow[i])
                p._data = self._slow[i]

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        out = self.inner_optimizer.minimize(loss)
        self._steps += 1
        if self._slow is None:
            self._slow = [jnp.array(p._data) for p in self._params()]
        if self._steps % self.k == 0:
            for i, p in enumerate(self._params()):
                self._slow[i] = self._slow[i] + self.alpha * (
                    p._data - self._slow[i])
                p._data = self._slow[i]
        return out
