"""Optimizers.

Reference parity: ``python/paddle/optimizer/optimizer.py`` (base) and the
per-op kernels in ``paddle/fluid/operators/optimizers/`` (sgd_op, momentum_op,
adam_op, adamw, lamb_op, lars_momentum_op, adagrad, rmsprop, adadelta).

TPU-native design: each optimizer is a **pure functional update rule**
``_update(param, grad, state, lr, ...) -> (new_param, new_state)`` over jax
arrays.  The eager facade (``step()``) applies it per-parameter; the jit path
(hapi / fleet train steps) applies the SAME rule over whole pytrees inside a
compiled step — one fused XLA kernel for the entire update, which is what the
reference's fuse_optimizer_ops_pass approximated by hand.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import autograd
from . import lr  # noqa: F401
from .lr import LRScheduler


class Optimizer:
    #: update rule is strictly per-element (safe to fuse across params);
    #: LAMB/LARS-style per-PARAM trust ratios must keep this False
    _elementwise_rule = False
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        if grad_clip is None:
            # 1.x fluid.clip.set_gradient_clip registers a process-wide
            # default consumed by optimizers built without an explicit
            # grad_clip (reference: fluid/clip.py set_gradient_clip)
            from ..nn import clip as _clip_mod
            grad_clip = _clip_mod.get_gradient_clip()
        self._grad_clip = grad_clip
        self._weight_decay = self._parse_wd(weight_decay)
        self._accumulators: dict[int, dict] = {}
        self._step_count = 0
        # opt-in flat-slab fused update (see _fused_flat_update);
        # PADDLE_TPU_FUSE_OPT=1 enables globally, or set
        # opt.fuse_update = True per instance
        import os as _os
        self.fuse_update = _os.environ.get(
            "PADDLE_TPU_FUSE_OPT", "0") == "1"

    @staticmethod
    def _parse_wd(weight_decay):
        if weight_decay is None:
            return 0.0
        if isinstance(weight_decay, (int, float)):
            return float(weight_decay)
        # regularizer object (L2Decay) with a coeff attribute
        return float(getattr(weight_decay, "_coeff",
                             getattr(weight_decay, "coeff", 0.0)))

    # -- lr ---------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def _get_param_lr(self, p):
        mult = 1.0
        attr = getattr(p, "optimize_attr", None)
        if attr:
            mult = attr.get("learning_rate", 1.0)
        return self.get_lr() * mult

    # -- functional core (overridden per optimizer) -----------------------
    def _init_state(self, param):
        """-> dict of state arrays for one param."""
        return {}

    def _update(self, param, grad, state, lr):
        """pure: (param, grad, state dicts of arrays, lr) ->
        (new_param, new_state)."""
        raise NotImplementedError

    def _update_sparse(self, param, rows, vals, state, lr):
        """Sparse (SelectedRows) update: `rows` are unique indices into
        dim 0 of `param`, `vals` the merged per-row gradients (reference:
        sparse kernels in operators/optimizers/, e.g. adam_op.h
        SparseAdamFunctor).  Base fallback densifies — correct for every
        rule; SGD/Momentum/Adam override with row-wise math."""
        g = jnp.zeros(param.shape, vals.dtype).at[rows].add(vals)
        return self._update(param, g, state, lr)

    # -- pytree API for jit'd train steps ---------------------------------
    def init_state_tree(self, params_tree):
        return jax.tree_util.tree_map(
            lambda p: self._init_state(p), params_tree,
            is_leaf=lambda x: isinstance(x, jnp.ndarray) or hasattr(
                x, "shape"))

    def apply_gradients_tree(self, params_tree, grads_tree, state_tree,
                             lr, fuse=None):
        """Pure whole-tree update; call inside jit.  ``fuse`` overrides
        ``self.fuse_update`` for this call — TrainStep passes False when
        params are sharded (the flat-slab concat would all-gather
        TP/FSDP/pp shards) without mutating the caller's optimizer."""
        if self._grad_clip is not None:
            grads_tree = self._grad_clip.apply_tree(grads_tree)
        flat_kp, treedef = jax.tree_util.tree_flatten_with_path(params_tree)
        names = ["/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                          for k in path) for path, _ in flat_kp]
        flat_p = [p for _, p in flat_kp]
        flat_g = treedef.flatten_up_to(grads_tree)
        flat_s = treedef.flatten_up_to(state_tree)
        has_mask = hasattr(self, "_decay_for_name")
        fuse = self.fuse_update if fuse is None else fuse
        # fused path requires all-dense grads: a None grad this call
        # would leave that param's SCALAR state (beta pows) lagging its
        # future group — sharing the group scalar would then silently
        # mis-correct it (see _fused_flat_update's precondition)
        if fuse and self._elementwise_rule \
                and not any(g is None for g in flat_g):
            new_p, new_s = self._fused_flat_update(
                names, flat_p, flat_g, flat_s, lr, has_mask)
            return (jax.tree_util.tree_unflatten(treedef, new_p),
                    jax.tree_util.tree_unflatten(treedef, new_s))
        new_p, new_s = [], []
        for name, p, g, s in zip(names, flat_p, flat_g, flat_s):
            if g is None:
                new_p.append(p)
                new_s.append(s)
                continue
            if has_mask:
                np_, ns = self._update(p, g, s, lr,
                                       decay_on=self._decay_for_name(name))
            else:
                np_, ns = self._update(p, g, s, lr)
            new_p.append(np_)
            new_s.append(ns)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                jax.tree_util.tree_unflatten(treedef, new_s))

    def _fused_flat_update(self, names, flat_p, flat_g, flat_s, lr,
                           has_mask):
        """Flat-slab update: concatenate params that share (decay mask,
        dtype, state layout) into one vector and run the elementwise
        update rule ONCE per group instead of once per parameter.  A
        ~150-param transformer becomes 2-3 fused update chains over
        large contiguous vectors — the per-parameter path emits hundreds
        of tiny fusions whose fixed overhead the profiler shows in the
        dominant elementwise bucket (BASELINE.md round-3 breakdown).
        Bitwise-equivalent math: every update rule here is per-element,
        scalar state (beta pows) follows an identical trajectory for
        every group member, and concat/split do not touch values.  Only
        rules marked ``_elementwise_rule`` may fuse (LAMB/LARS use
        per-PARAM trust ratios and must stay per-parameter).

        PRECONDITION: every group member's scalar state is equal — true
        whenever all params have stepped together since init (the
        compiled TrainStep path).  The caller falls back to per-param
        whenever any grad is None, so a lag cannot be INTRODUCED through
        this API; state hand-built with divergent scalars is the
        caller's responsibility."""
        import numpy as _np
        groups = {}
        for i, (name, p, g, s) in enumerate(
                zip(names, flat_p, flat_g, flat_s)):
            if g is None:
                continue
            decay_on = self._decay_for_name(name) if has_mask else True
            skey = tuple(sorted(
                (k, str(v.dtype), int(v.ndim)) for k, v in s.items())) \
                if isinstance(s, dict) else ()
            # grad dtype in the key too: mixed-dtype grads within one
            # group would be silently promoted by jnp.concatenate,
            # diverging from the per-param path's native-dtype math
            groups.setdefault(
                (bool(decay_on), str(p.dtype), str(g.dtype), skey),
                []).append(i)
        new_p, new_s = list(flat_p), list(flat_s)
        for (decay_on, _, _, _), idxs in groups.items():
            # _np.prod(()) == 1.0 (scalars); zero-size params correctly
            # contribute empty slices
            sizes = [int(_np.prod(flat_p[i].shape)) for i in idxs]
            offs = _np.cumsum(sizes)[:-1].tolist()
            fp = jnp.concatenate(
                [flat_p[i].reshape(-1) for i in idxs])
            fg = jnp.concatenate(
                [flat_g[i].reshape(-1) for i in idxs])
            s0 = flat_s[idxs[0]]
            fs = {k: (v if v.ndim == 0 else jnp.concatenate(
                [flat_s[i][k].reshape(-1) for i in idxs]))
                for k, v in s0.items()} if isinstance(s0, dict) else s0
            if has_mask:
                nfp, nfs = self._update(fp, fg, fs, lr,
                                        decay_on=decay_on)
            else:
                nfp, nfs = self._update(fp, fg, fs, lr)
            p_parts = jnp.split(nfp, offs)
            s_parts = {k: (jnp.split(v, offs) if v.ndim else v)
                       for k, v in nfs.items()} \
                if isinstance(nfs, dict) else nfs
            for j, i in enumerate(idxs):
                new_p[i] = p_parts[j].reshape(flat_p[i].shape)
                if isinstance(nfs, dict):
                    new_s[i] = {
                        k: (s_parts[k][j].reshape(flat_s[i][k].shape)
                            if nfs[k].ndim else s_parts[k])
                        for k in nfs}
                else:
                    new_s[i] = nfs
        return new_p, new_s

    # -- eager facade -----------------------------------------------------
    def _params(self):
        if self._parameter_list is None:
            raise ValueError(
                "Optimizer needs `parameters=` in eager (dygraph) mode")
        return self._parameter_list

    def step(self):
        self._step_count += 1
        params = [p for p in self._params() if p.trainable]
        pg = [(p, p.grad) for p in params if p.grad is not None]
        if self._grad_clip is not None:
            pg = self._grad_clip(pg)
        from ..core.selected_rows import SelectedRows
        with autograd.no_grad():
            for p, g in pg:
                if g is None:
                    continue
                key = id(p)
                if key not in self._accumulators:
                    self._accumulators[key] = self._init_state(p)
                state = self._accumulators[key]
                if isinstance(g, SelectedRows):
                    rows, vals = g.merged()
                    new_param, new_state = self._update_sparse(
                        p._data, rows, vals.astype(p._data.dtype), state,
                        self._get_param_lr(p))
                else:
                    new_param, new_state = self._update(
                        p._data, g._data.astype(p._data.dtype), state,
                        self._get_param_lr(p))
                p._data = new_param
                self._accumulators[key] = new_state

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static import program as sprog
        if isinstance(loss, sprog.Variable):
            # static graph mode (reference: Optimizer.minimize appending
            # grad + optimize ops to the program, fluid/optimizer.py)
            pairs = sprog.append_backward(
                loss,
                parameter_list=parameters or self._parameter_list or None)
            sprog.append_optimize(self, loss, pairs)
            return None, pairs
        params = [p for p in self._params() if p.trainable]
        if builtins_all(p.grad is None for p in params) and \
                loss._grad_node is not None:
            loss.backward()
        self.step()
        return None, [(p, p.grad) for p in params]

    def clear_grad(self, set_to_zero=True):
        for p in self._params():
            p.clear_grad()

    clear_gradients = clear_grad

    # -- state ------------------------------------------------------------
    def state_dict(self):
        out = {"__step__": self._step_count}
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        if self._parameter_list is not None:
            for i, p in enumerate(self._parameter_list):
                state = self._accumulators.get(id(p))
                if state:
                    for k, v in state.items():
                        out[f"{p.name}__{k}"] = Tensor(v) if not isinstance(
                            v, Tensor) else v
        return out

    def set_state_dict(self, state):
        self._step_count = state.get("__step__", 0)
        if isinstance(self._learning_rate, LRScheduler) and \
                "LR_Scheduler" in state:
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        if self._parameter_list is None:
            return
        for p in self._parameter_list:
            st = {}
            prefix = f"{p.name}__"
            for k, v in state.items():
                if isinstance(k, str) and k.startswith(prefix):
                    st[k[len(prefix):]] = (v._data if isinstance(v, Tensor)
                                           else jnp.asarray(v))
            if st:
                self._accumulators[id(p)] = st

    set_dict = set_state_dict


builtins_all = all


class SGD(Optimizer):
    """reference: operators/optimizers/sgd_op.cc"""
    _elementwise_rule = True

    def _update(self, param, grad, state, lr):
        if self._weight_decay:
            grad = grad + self._weight_decay * param
        return param - lr * grad, state

    def _update_sparse(self, param, rows, vals, state, lr):
        # reference: sgd_op.h SelectedRows branch — scatter-subtract the
        # touched rows only.  With weight_decay, decay applies to touched
        # rows (the reference rejects regularizers on sparse params
        # outright; scoping decay to touched rows is the sparse semantic).
        if self._weight_decay:
            vals = vals + self._weight_decay * param[rows]
        return param.at[rows].add(-lr * vals), state


class Momentum(Optimizer):
    """reference: operators/optimizers/momentum_op.cc"""
    _elementwise_rule = True

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, param):
        shape = param.shape if hasattr(param, "shape") else ()
        dtype = param._data.dtype if isinstance(param, Tensor) else \
            param.dtype
        return {"velocity": jnp.zeros(shape, dtype)}

    def _update(self, param, grad, state, lr):
        if self._weight_decay:
            grad = grad + self._weight_decay * param
        v = self._momentum * state["velocity"] + grad
        if self._nesterov:
            new_param = param - lr * (grad + self._momentum * v)
        else:
            new_param = param - lr * v
        return new_param, {"velocity": v}

    def _update_sparse(self, param, rows, vals, state, lr):
        # reference: momentum_op.h SparseMomentumFunctor — missing rows
        # carry zero grad, so velocity still decays everywhere; grads and
        # decay land only on the touched rows.  Matches the dense rule
        # exactly when weight_decay == 0.
        if self._weight_decay:
            vals = vals + self._weight_decay * param[rows]
        v = self._momentum * state["velocity"]
        v = v.at[rows].add(vals)
        if self._nesterov:
            new_param = param - lr * self._momentum * v
            new_param = new_param.at[rows].add(-lr * vals)
        else:
            new_param = param - lr * v
        return new_param, {"velocity": v}


class Adam(Optimizer):
    """reference: operators/optimizers/adam_op.cc (with bias correction)."""
    _elementwise_rule = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy = bool(lazy_mode)
        self._multi_precision = bool(multi_precision)

    def _init_state(self, param):
        shape = param.shape if hasattr(param, "shape") else ()
        dtype = param._data.dtype if isinstance(param, Tensor) else \
            param.dtype
        # multi_precision (default, reference: adam_op MasterParam): f32
        # moments for low-precision params.  multi_precision=False keeps
        # moments in the PARAM dtype — halves optimizer-state HBM for
        # bf16 models (2 x 2 bytes/param instead of 2 x 4), the knob the
        # single-chip GPT-3 1.3B fit relies on
        if dtype in (jnp.bfloat16, jnp.float16):
            mdtype = jnp.float32 if self._multi_precision else dtype
        else:
            mdtype = dtype
        return {"moment1": jnp.zeros(shape, mdtype),
                "moment2": jnp.zeros(shape, mdtype),
                "beta1_pow": jnp.ones([], jnp.float32),
                "beta2_pow": jnp.ones([], jnp.float32)}

    def _update_sparse(self, param, rows, vals, state, lr):
        """reference: adam_op.h SparseAdamFunctor.  lazy_mode=True (the
        flag the dense path ignores) updates moments and param ONLY at the
        touched rows — O(batch) work, the embedding-table fast path.
        lazy_mode=False reproduces the dense rule exactly: missing rows
        see zero grad, so their moments decay and bias-corrected updates
        still move them."""
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        mdtype = state["moment1"].dtype
        g = vals.astype(mdtype)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        decay = self._weight_decay if isinstance(self, AdamW) else 0.0
        if not isinstance(self, AdamW) and self._weight_decay:
            # L2-reg folds into the gradient; sparse semantic scopes it
            # to touched rows (see SGD._update_sparse note)
            g = g + self._weight_decay * param[rows].astype(mdtype)
        if self._lazy:
            m_r = b1 * state["moment1"][rows] + (1 - b1) * g
            v_r = b2 * state["moment2"][rows] + (1 - b2) * jnp.square(g)
            update = (m_r / (1 - b1p)) / (jnp.sqrt(v_r / (1 - b2p)) + eps)
            p_r = param[rows].astype(update.dtype)
            if decay and self._decay_allows_rows(param):
                update = update + decay * p_r
            new_param = param.at[rows].set(
                (p_r - lr * update).astype(param.dtype))
            m = state["moment1"].at[rows].set(m_r)
            v = state["moment2"].at[rows].set(v_r)
        else:
            m = b1 * state["moment1"]
            m = m.at[rows].add((1 - b1) * g)
            v = b2 * state["moment2"]
            v = v.at[rows].add((1 - b2) * jnp.square(g))
            update = (m / (1 - b1p)) / (jnp.sqrt(v / (1 - b2p)) + eps)
            if decay and self._decay_allows_rows(param):
                update = update + decay * param.astype(update.dtype)
            new_param = (param.astype(update.dtype) - lr * update).astype(
                param.dtype)
        return new_param, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                           "beta2_pow": b2p}

    def _decay_allows_rows(self, param):
        fn = getattr(self, "_apply_decay_fn", None)
        return fn is None or fn(param)

    def _update(self, param, grad, state, lr, decay_on=True):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        g = grad.astype(state["moment1"].dtype)
        if self._weight_decay and not isinstance(self, AdamW):
            g = g + self._weight_decay * param.astype(g.dtype)
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        update = m_hat / (jnp.sqrt(v_hat) + eps)
        if isinstance(self, AdamW) and self._weight_decay and decay_on:
            if self._apply_decay_fn is None or self._apply_decay_fn(param):
                update = update + self._weight_decay * param.astype(
                    update.dtype)
        new_param = (param.astype(update.dtype) - lr * update).astype(
            param.dtype)
        return new_param, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                           "beta2_pow": b2p}


class AdamW(Adam):
    """reference: operators/optimizers/adamw (decoupled decay)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=True, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         name)
        # paddle passes the param NAME to the predicate
        self._decay_param_fun = apply_decay_param_fun
        self._apply_decay_fn = None

    def _decay_for_name(self, name):
        """Used by the jit/tree path; `name` is the pytree path (the
        train-step builder keys params by their layer-qualified name)."""
        if self._decay_param_fun is None:
            return True
        return bool(self._decay_param_fun(name))

    def step(self):
        # resolve name-based decay predicate into per-step closure
        if self._decay_param_fun is not None:
            fn = self._decay_param_fun
            names = {id(p._data): fn(p.name) for p in self._params()}

            def pred(param):
                return names.get(id(param), True)
            self._apply_decay_fn = pred
        super().step()
        self._apply_decay_fn = None


class Adamax(Optimizer):
    _elementwise_rule = True
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, param):
        shape = param.shape if hasattr(param, "shape") else ()
        dtype = param._data.dtype if isinstance(param, Tensor) else \
            param.dtype
        return {"moment": jnp.zeros(shape, dtype),
                "inf_norm": jnp.zeros(shape, dtype),
                "beta1_pow": jnp.ones([], jnp.float32)}

    def _update(self, param, grad, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        if self._weight_decay:
            grad = grad + self._weight_decay * param
        m = b1 * state["moment"] + (1 - b1) * grad
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(grad))
        b1p = state["beta1_pow"] * b1
        new_param = param - lr / (1 - b1p) * m / (u + eps)
        return new_param.astype(param.dtype), {
            "moment": m, "inf_norm": u, "beta1_pow": b1p}


class Adagrad(Optimizer):
    _elementwise_rule = True
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_value = initial_accumulator_value

    def _init_state(self, param):
        shape = param.shape if hasattr(param, "shape") else ()
        dtype = param._data.dtype if isinstance(param, Tensor) else \
            param.dtype
        return {"moment": jnp.full(shape, self._init_value, dtype)}

    def _update(self, param, grad, state, lr):
        if self._weight_decay:
            grad = grad + self._weight_decay * param
        m = state["moment"] + jnp.square(grad)
        new_param = param - lr * grad / (jnp.sqrt(m) + self._epsilon)
        return new_param, {"moment": m}


class RMSProp(Optimizer):
    _elementwise_rule = True
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_state(self, param):
        shape = param.shape if hasattr(param, "shape") else ()
        dtype = param._data.dtype if isinstance(param, Tensor) else \
            param.dtype
        return {"mean_square": jnp.zeros(shape, dtype),
                "mean_grad": jnp.zeros(shape, dtype),
                "velocity": jnp.zeros(shape, dtype)}

    def _update(self, param, grad, state, lr):
        if self._weight_decay:
            grad = grad + self._weight_decay * param
        ms = self._rho * state["mean_square"] + (1 - self._rho) * \
            jnp.square(grad)
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * grad
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self._epsilon)
        v = self._momentum * state["velocity"] + lr * grad / denom
        return param - v, {"mean_square": ms, "mean_grad": mg, "velocity": v}


class Adadelta(Optimizer):
    _elementwise_rule = True
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._epsilon = rho, epsilon

    def _init_state(self, param):
        shape = param.shape if hasattr(param, "shape") else ()
        dtype = param._data.dtype if isinstance(param, Tensor) else \
            param.dtype
        return {"avg_squared_grad": jnp.zeros(shape, dtype),
                "avg_squared_update": jnp.zeros(shape, dtype)}

    def _update(self, param, grad, state, lr):
        if self._weight_decay:
            grad = grad + self._weight_decay * param
        rho, eps = self._rho, self._epsilon
        asg = rho * state["avg_squared_grad"] + (1 - rho) * jnp.square(grad)
        update = grad * jnp.sqrt(state["avg_squared_update"] + eps) / \
            jnp.sqrt(asg + eps)
        asu = rho * state["avg_squared_update"] + (1 - rho) * \
            jnp.square(update)
        return param - lr * update, {"avg_squared_grad": asg,
                                     "avg_squared_update": asu}


class Lamb(Optimizer):
    """reference: operators/optimizers/lamb_op.cc (layer-wise adaptation)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay,
                         grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        # paddle passes the Parameter object to the predicate
        self._exclude_fn = exclude_from_weight_decay_fn
        self._exclude_ids = None

    def _decay_for_name(self, name):
        """jit/tree path: predicate gets the pytree param name (the eager
        path passes the Parameter object, matching paddle)."""
        if self._exclude_fn is None:
            return True
        try:
            return not bool(self._exclude_fn(name))
        except Exception:
            return True

    def step(self):
        if self._exclude_fn is not None:
            self._exclude_ids = {
                id(p._data) for p in self._params()
                if self._exclude_fn(p)}
        super().step()
        self._exclude_ids = None

    def _init_state(self, param):
        shape = param.shape if hasattr(param, "shape") else ()
        dtype = param._data.dtype if isinstance(param, Tensor) else \
            param.dtype
        mdtype = jnp.float32 if dtype in (jnp.bfloat16, jnp.float16) \
            else dtype
        return {"moment1": jnp.zeros(shape, mdtype),
                "moment2": jnp.zeros(shape, mdtype),
                "beta1_pow": jnp.ones([], jnp.float32),
                "beta2_pow": jnp.ones([], jnp.float32)}

    def _update(self, param, grad, state, lr, decay_on=True):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        g = grad.astype(state["moment1"].dtype)
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + eps)
        excluded = (self._exclude_ids is not None
                    and id(param) in self._exclude_ids)
        if decay_on and self._weight_decay and not excluded:
            r = r + self._weight_decay * param.astype(r.dtype)
        w_norm = jnp.linalg.norm(param.astype(jnp.float32))
        r_norm = jnp.linalg.norm(r.astype(jnp.float32))
        trust = jnp.where(
            (w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_param = (param.astype(r.dtype) - lr * trust * r).astype(
            param.dtype)
        return new_param, {"moment1": m, "moment2": v, "beta1_pow": b1p,
                           "beta2_pow": b2p}


class LarsMomentum(Momentum):
    """reference: operators/optimizers/lars_momentum_op.cc"""
    _elementwise_rule = False  # per-param trust ratio

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, epsilon=1e-9, name=None):
        super().__init__(learning_rate, momentum, parameters,
                         weight_decay=None, grad_clip=grad_clip)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon

    def _update(self, param, grad, state, lr):
        w_norm = jnp.linalg.norm(param.astype(jnp.float32))
        g_norm = jnp.linalg.norm(grad.astype(jnp.float32))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            lr * self._lars_coeff * w_norm
            / (g_norm + self._lars_wd * w_norm + self._eps), lr)
        g = grad + self._lars_wd * param
        v = self._momentum * state["velocity"] + local_lr * g
        return param - v, {"velocity": v}


Lars = LarsMomentum
