"""paddle.regularizer (reference: python/paddle/regularizer.py, fluid
regularizer.py).  Consumed by Optimizer weight_decay via the `_coeff`
attribute; L1 is applied as a grad transform in the optimizer base."""
from __future__ import annotations


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self.coeff = self._coeff

    def __repr__(self):
        return f"L2Decay({self._coeff})"


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self.coeff = self._coeff
        self.l1 = True

    def __repr__(self):
        return f"L1Decay({self._coeff})"


# 1.x class names (reference: fluid/regularizer.py)
L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
