"""Op library aggregator.

Reference parity: the Python dispatch layer ``python/paddle/tensor/*`` which
forwards to ``core.ops.*``.  Here every op is a pure-jax function wrapped by
``core.dispatch.primitive``; this module also attaches operator dunders and
method forms onto :class:`Tensor` (the reference does this via
``monkey_patch_varbase``/``monkey_patch_math_varbase``).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import primitive, ensure_tensor
from ..core import autograd

from .math import *  # noqa: F401,F403
from .creation import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from . import math as _math
from . import creation as _creation
from . import manipulation as _manip
from . import linalg  # noqa: F401


# ---- indexing -----------------------------------------------------------
def _prep_index(idx):
    if isinstance(idx, tuple):
        return tuple(_prep_index(i) for i in idx)
    if isinstance(idx, Tensor):
        arr = idx._data
        if jnp.issubdtype(arr.dtype, jnp.bool_):
            return np.asarray(arr)  # boolean mask: host (dynamic shape)
        return arr
    if isinstance(idx, (list, np.ndarray)):
        return np.asarray(idx)
    return idx


def _getitem(x, idx):
    idx = _prep_index(idx)
    prim = primitive(name="slice")(lambda a: a[idx])
    return prim(x)


def _setitem(x, idx, value):
    idx = _prep_index(idx)
    v = value._data if isinstance(value, Tensor) else jnp.asarray(
        value, x._data.dtype)
    if not x.stop_gradient and autograd.grad_enabled():
        prim = primitive(name="set_value")(
            lambda a, b: a.at[idx].set(b.astype(a.dtype)))
        val = value if isinstance(value, Tensor) else Tensor(v)
        autograd.run_inplace(x, prim, val)
    else:
        x._data = x._data.at[idx].set(jnp.asarray(v, x._data.dtype))
    return x


# ---- operator attachment ------------------------------------------------
def _attach():
    T = Tensor
    T.__getitem__ = _getitem
    T.__setitem__ = _setitem

    T.__add__ = lambda s, o: _math.add(s, o)
    T.__radd__ = lambda s, o: _math.add(o, s)
    T.__sub__ = lambda s, o: _math.subtract(s, o)
    T.__rsub__ = lambda s, o: _math.subtract(ensure_tensor(o, ref=s), s)
    T.__mul__ = lambda s, o: _math.multiply(s, o)
    T.__rmul__ = lambda s, o: _math.multiply(o, s)
    T.__truediv__ = lambda s, o: _math.divide(s, o)
    T.__rtruediv__ = lambda s, o: _math.divide(ensure_tensor(o, ref=s), s)
    T.__floordiv__ = lambda s, o: _math.floor_divide(s, o)
    T.__rfloordiv__ = lambda s, o: _math.floor_divide(
        ensure_tensor(o, ref=s), s)
    T.__mod__ = lambda s, o: _math.remainder(s, o)
    T.__pow__ = lambda s, o: _math.pow_(s, o)
    T.__rpow__ = lambda s, o: _math.pow_(ensure_tensor(o, ref=s), s)
    T.__matmul__ = lambda s, o: _math.matmul(s, o)
    T.__neg__ = lambda s: _math.neg(s)
    T.__abs__ = lambda s: _math.abs(s)
    T.__invert__ = lambda s: _math.logical_not(s)

    T.__eq__ = lambda s, o: _math.equal(s, o)
    T.__ne__ = lambda s, o: _math.not_equal(s, o)
    T.__lt__ = lambda s, o: _math.less_than(s, o)
    T.__le__ = lambda s, o: _math.less_equal(s, o)
    T.__gt__ = lambda s, o: _math.greater_than(s, o)
    T.__ge__ = lambda s, o: _math.greater_equal(s, o)

    method_map = {
        # math
        "add": _math.add, "subtract": _math.subtract,
        "multiply": _math.multiply, "divide": _math.divide,
        "mod": _math.remainder, "remainder": _math.remainder,
        "floor_divide": _math.floor_divide, "pow": _math.pow,
        "matmul": _math.matmul, "mm": _math.mm, "bmm": _math.bmm,
        "dot": _math.dot, "abs": _math.abs, "neg": _math.neg,
        "sqrt": _math.sqrt, "rsqrt": _math.rsqrt, "square": _math.square,
        "exp": _math.exp, "log": _math.log, "log2": _math.log2,
        "log10": _math.log10, "log1p": _math.log1p,
        "sin": _math.sin, "cos": _math.cos, "tan": _math.tan,
        "tanh": _math.tanh, "sigmoid": _math.sigmoid, "erf": _math.erf,
        "floor": _math.floor, "ceil": _math.ceil, "round": _math.round,
        "trunc": _math.trunc, "sign": _math.sign,
        "reciprocal": _math.reciprocal, "clip": _math.clip,
        "scale": _math.scale, "maximum": _math.maximum,
        "minimum": _math.minimum,
        "sum": _math.sum, "mean": _math.mean, "prod": _math.prod,
        "max": _math.max, "min": _math.min, "var": _math.var,
        "std": _math.std, "all": _math.all, "any": _math.any,
        "logsumexp": _math.logsumexp, "cumsum": _math.cumsum,
        "cumprod": _math.cumprod, "isnan": _math.isnan,
        "isinf": _math.isinf, "isfinite": _math.isfinite,
        "equal": _math.equal, "not_equal": _math.not_equal,
        "less_than": _math.less_than, "less_equal": _math.less_equal,
        "greater_than": _math.greater_than,
        "greater_equal": _math.greater_equal,
        "equal_all": _math.equal_all, "allclose": _math.allclose,
        "isclose": _math.isclose,
        "logical_and": _math.logical_and, "logical_or": _math.logical_or,
        "logical_not": _math.logical_not, "logical_xor": _math.logical_xor,
        "trace": _math.trace,
        # manipulation
        "reshape": _manip.reshape, "reshape_": _manip.reshape_,
        "transpose": _manip.transpose, "t": _manip.t,
        "squeeze": _manip.squeeze, "unsqueeze": _manip.unsqueeze,
        "flatten": _manip.flatten, "flip": _manip.flip,
        "roll": _manip.roll, "tile": _manip.tile, "expand": _manip.expand,
        "expand_as": _manip.expand_as,
        "broadcast_to": _manip.broadcast_to, "gather": _manip.gather,
        "gather_nd": _manip.gather_nd, "scatter": _manip.scatter,
        "scatter_nd_add": _manip.scatter_nd_add,
        "index_select": _manip.index_select,
        "masked_select": _manip.masked_select,
        "masked_fill": _manip.masked_fill,
        "where": _manip.where, "nonzero": _manip.nonzero,
        "argmax": _manip.argmax, "argmin": _manip.argmin,
        "argsort": _manip.argsort, "sort": _manip.sort,
        "topk": _manip.topk, "unique": _manip.unique,
        "split": _manip.split, "chunk": _manip.chunk,
        "unbind": _manip.unbind, "concat": None,
        "take_along_axis": _manip.take_along_axis,
        "repeat_interleave": _manip.repeat_interleave,
        "one_hot": _manip.one_hot,
        "norm": linalg.norm, "dist": linalg.dist,
        "numel": _math.numel,
    }
    for name, fn in method_map.items():
        if fn is None:
            continue
        if not hasattr(T, name):
            setattr(T, name, fn)


_attach()
