"""py_func: run arbitrary Python (numpy) code as an op inside graphs.

Reference parity: ``operators/py_func_op.cc`` (host-side op whose kernel
re-enters the Python interpreter) + ``python/paddle/fluid/layers/nn.py``
``py_func`` (user API: ``func`` fills pre-declared ``out`` vars;
``backward_func`` receives forward inputs + outputs + output-gradients —
minus ``skip_vars_in_backward_input`` — and returns gradients of ``x``).

TPU-native design: the host round-trip is ``jax.pure_callback`` — XLA
inserts a host callback custom-call, so the op works inside ``jit``,
``@to_static`` traces and recorded static Programs alike (the reference
needed a dedicated C++ operator holding Python function registry ids;
here the closure IS the registry).  ``backward_func`` becomes the bwd
rule of a ``jax.custom_vjp`` wrapped around the callback, so the same
one implementation serves the eager tape, static ``append_backward``
replay, and ``jax.grad`` through compiled train steps.  Integer inputs
take ``float0`` cotangents per JAX convention (the reference likewise
never produces grads for integer vars).
"""
from __future__ import annotations

import numpy as np


def _spec_of(t):
    """(shape, numpy dtype) of a Tensor / Variable / shaped template."""
    data = getattr(t, "_data", t)
    return tuple(int(d) for d in data.shape), np.dtype(data.dtype)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """Record ``out = func(*x)`` executed by the Python interpreter.

    ``out`` declares the result template(s): Tensor/Variable(s) (e.g.
    from ``static.data`` or ``create_parameter``) whose shape/dtype the
    callback's results must match — mirroring the reference where the
    caller pre-creates the out vars (``fluid/layers/nn.py`` py_func).
    Returns new tensors in the same single/list structure as ``out``.
    """
    import jax

    from ..core.dispatch import primitive
    from ..core.tensor import Tensor

    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    single_out = not isinstance(out, (list, tuple))
    if not callable(func):
        raise TypeError("py_func: func must be callable")
    out_specs = [_spec_of(o) for o in outs]
    result_struct = tuple(jax.ShapeDtypeStruct(s, d) for s, d in out_specs)

    skip = skip_vars_in_backward_input
    skip = [] if skip is None else (
        list(skip) if isinstance(skip, (list, tuple)) else [skip])
    known = {id(v) for v in xs} | {id(v) for v in outs}
    for v in skip:
        if id(v) not in known:
            raise ValueError(
                "py_func: every skip_vars_in_backward_input entry must "
                "be one of x or out (reference fluid/layers/nn.py "
                "py_func checks the same)")
    skip_ids = {id(v) for v in skip}
    keep_x = [i for i, v in enumerate(xs) if id(v) not in skip_ids]
    keep_y = [i for i, v in enumerate(outs) if id(v) not in skip_ids]

    def _host_forward(*arrays):
        res = func(*[np.asarray(a) for a in arrays])
        res = list(res) if isinstance(res, (list, tuple)) else [res]
        if len(res) != len(out_specs):
            raise ValueError(
                f"py_func: func returned {len(res)} values, out "
                f"declares {len(out_specs)}")
        return tuple(
            np.ascontiguousarray(np.asarray(r), dtype=d).reshape(s)
            for r, (s, d) in zip(res, out_specs))

    def _callback_forward(*arrays):
        res = jax.pure_callback(_host_forward, result_struct, *arrays)
        return tuple(res)

    if backward_func is None:
        # no grad path at all: mirror the reference, where a py_func
        # without backward_func contributes no gradient op
        jax_fn = _callback_forward
        nondiff = tuple(range(len(xs)))
    else:
        nondiff = ()

        def jax_fn(*arrays):
            import jax.numpy as jnp
            n_x = len(arrays)
            grad_pos = [i for i in range(n_x) if np.issubdtype(
                np.dtype(arrays[i].dtype), np.floating)]
            grad_struct = tuple(
                jax.ShapeDtypeStruct(arrays[i].shape, arrays[i].dtype)
                for i in grad_pos)

            def _host_backward(*bw_arrays):
                gs = backward_func(*[np.asarray(b) for b in bw_arrays])
                gs = list(gs) if isinstance(gs, (list, tuple)) else [gs]
                if len(gs) != n_x:
                    raise ValueError(
                        f"py_func: backward_func returned {len(gs)} "
                        f"gradients for {n_x} inputs")
                picked = []
                for i in grad_pos:
                    g, (shape, dt) = gs[i], (
                        tuple(int(d) for d in grad_struct[
                            grad_pos.index(i)].shape),
                        np.dtype(grad_struct[grad_pos.index(i)].dtype))
                    picked.append(
                        np.zeros(shape, dt) if g is None else
                        np.ascontiguousarray(
                            np.asarray(g), dtype=dt).reshape(shape))
                return tuple(picked)

            @jax.custom_vjp
            def core(*args):
                return _callback_forward(*args)

            def _fwd(*args):
                ys = _callback_forward(*args)
                return ys, (args, ys)

            def _bwd(res, cts):
                p_args, ys = res
                # integer/bool outputs carry float0 cotangents, which
                # cannot cross the callback boundary — hand the host
                # zeros of the output dtype instead (the reference
                # likewise passes no real grad for integer outs)
                cts = [jnp.zeros(y.shape, y.dtype)
                       if getattr(ct, "dtype", None) == jax.dtypes.float0
                       else ct for ct, y in zip(cts, ys)]
                host_in = ([p_args[i] for i in keep_x]
                           + [ys[i] for i in keep_y] + list(cts))
                if grad_pos:
                    gouts = jax.pure_callback(
                        _host_backward, grad_struct, *host_in)
                    gouts = list(gouts)
                else:
                    gouts = []
                full = []
                for i, a in enumerate(p_args):
                    if i in grad_pos:
                        full.append(gouts[grad_pos.index(i)])
                    else:  # integer/bool inputs: float0 cotangents
                        full.append(np.zeros(a.shape, jax.dtypes.float0))
                return tuple(full)

            core.defvjp(_fwd, _bwd)
            return core(*arrays)

    op = primitive(name="py_func", nondiff=nondiff)(jax_fn)
    res = op(*xs)
    res = list(res) if isinstance(res, tuple) else [res]
    if single_out:
        return res[0]
    return res
