"""Tensor creation ops.

Reference parity: fill_constant_op.cc, uniform_random_op.cc,
gaussian_random_op.cc, range_op.cc, linspace_op.cc, eye_op.cc,
tril_triu_op.cc, diag_v2_op.cc, assign_op.cc.
Random ops draw from the global counter-based generator (core/rng.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import primitive, ensure_tensor
from ..core import dtype as dtypes
from ..core import rng


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        shape = [shape]
    return tuple(int(s) for s in shape)


def _dt(dtype, default=None):
    return dtypes.to_jax(dtype if dtype is not None else
                         (default or dtypes.get_default_dtype()))


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None and isinstance(fill_value, bool):
        dtype = "bool"
    elif dtype is None and isinstance(fill_value, int):
        dtype = "int64"
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.zeros_like(x._data, _dt(dtype, x.dtype)))


def ones_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.ones_like(x._data, _dt(dtype, x.dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.full_like(x._data, fill_value, _dt(dtype, x.dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, float):
            dtype = dtype or "float32"
    if isinstance(start, Tensor):
        start = start.item()
    if isinstance(end, Tensor):
        end = end.item()
    if isinstance(step, Tensor):
        step = step.item()
    return Tensor(jnp.arange(start, end, step, _dt(dtype, "int64")))


def linspace(start, stop, num, dtype=None, name=None):
    if isinstance(start, Tensor):
        start = start.item()
    if isinstance(stop, Tensor):
        stop = stop.item()
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(float(start), float(stop), int(num),
                               base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns else None,
                          dtype=_dt(dtype)))


@primitive(name="tril")
def _tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


def tril(x, diagonal=0, name=None):
    return _tril(ensure_tensor(x), diagonal=int(diagonal))


@primitive(name="triu")
def _triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def triu(x, diagonal=0, name=None):
    return _triu(ensure_tensor(x), diagonal=int(diagonal))


@primitive(name="diag")
def _diag(x, offset=0):
    return jnp.diag(x, k=offset)


def diag(x, offset=0, padding_value=0, name=None):
    x = ensure_tensor(x)
    if x.ndim == 1 and padding_value != 0:
        n = x.shape[0] + builtins_abs(offset)
        base = jnp.full((n, n), padding_value, x._data.dtype)
        out = base + jnp.diag(x._data - padding_value, k=offset)
        return Tensor(out)
    return _diag(x, offset=int(offset))


builtins_abs = abs


def diagflat(x, offset=0, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.diagflat(x._data, k=offset))


def meshgrid(*args, name=None):
    arrays = [ensure_tensor(a)._data for a in
              (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple))
               else args)]
    outs = jnp.meshgrid(*arrays, indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    """reference: operators/assign_op.cc"""
    x = ensure_tensor(x)
    out = primitive(name="assign")(lambda a: a + 0)(x)
    if output is not None:
        output.set_value(out)
        return output
    return out


def clone(x, name=None):
    return assign(x)


# ---- random (reference: uniform_random_op.cc etc. + generator.cc) -------
def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(rng.next_key(), _shape(shape),
                                     _dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(rng.next_key(), _shape(shape),
                                    _dt(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = rng.key_for(seed)
    return Tensor(jax.random.uniform(key, _shape(shape), _dt(dtype),
                                     minval=float(min), maxval=float(max)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = ensure_tensor(mean)._data if isinstance(mean, Tensor) else mean
        s = ensure_tensor(std)._data if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(
            getattr(m, "shape", ()), getattr(s, "shape", ()))
        eps = jax.random.normal(rng.next_key(), out_shape,
                                _dt(None))
        return Tensor(m + s * eps)
    return Tensor(mean + std * jax.random.normal(
        rng.next_key(), _shape(shape or [1]), _dt(None)))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(rng.next_key(), _shape(shape),
                                     int(low), int(high),
                                     _dt(dtype, "int64")))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = ensure_tensor(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(rng.next_key(), int(n)).astype(
        _dt(dtype, "int64")))


def bernoulli(x, name=None):
    x = ensure_tensor(x)
    u = jax.random.uniform(rng.next_key(), tuple(x.shape), jnp.float32)
    return Tensor((u < x._data).astype(x._data.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = ensure_tensor(x)
    logits = jnp.log(jnp.maximum(x._data, 1e-30))
    if replacement:
        out = jax.random.categorical(
            rng.next_key(), logits, axis=-1,
            shape=(*logits.shape[:-1], int(num_samples)))
    else:
        # Gumbel top-k for sampling without replacement
        g = jax.random.gumbel(rng.next_key(), logits.shape)
        _, out = jax.lax.top_k(logits + g, int(num_samples))
    return Tensor(out.astype(jnp.int64))


def poisson(x, name=None):
    x = ensure_tensor(x)
    return Tensor(jax.random.poisson(rng.next_key(), x._data).astype(
        x._data.dtype))
