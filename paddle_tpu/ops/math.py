"""Math ops.

Reference parity: the dense math core of ``paddle/fluid/operators``
(elementwise/*, reduce_ops/*, activation_op.cc, matmul_v2_op, scale_op,
clip_op, cumsum_op, …).  Each op is ONE pure jax function — XLA provides all
backends and the fusion the reference implemented by hand (e.g.
fused_elemwise_activation).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import primitive, ensure_tensor
from ..core.tensor import Tensor
from ..core import dtype as dtypes


def _binary(name, fn):
    prim = primitive(name=name)(fn)

    def op(x, y, name=None):
        x = ensure_tensor(x, ref=y if isinstance(y, Tensor) else None)
        y = ensure_tensor(y, ref=x)
        return prim(x, y)

    op.__name__ = name
    return op


def _unary(name, fn):
    prim = primitive(name=name)(fn)

    def op(x, name=None):
        return prim(ensure_tensor(x))

    op.__name__ = name
    return op


# ---- elementwise binary (reference: operators/elementwise/) -------------
add = _binary("elementwise_add", jnp.add)
subtract = _binary("elementwise_sub", jnp.subtract)
multiply = _binary("elementwise_mul", jnp.multiply)
divide = _binary("elementwise_div", jnp.true_divide)
floor_divide = _binary("elementwise_floordiv", jnp.floor_divide)
remainder = _binary("elementwise_mod", jnp.remainder)
mod = remainder
floor_mod = remainder
pow_ = _binary("elementwise_pow", jnp.power)
maximum = _binary("elementwise_max", jnp.maximum)
minimum = _binary("elementwise_min", jnp.minimum)
fmax = _binary("elementwise_fmax", jnp.fmax)
fmin = _binary("elementwise_fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
hypot = _binary("hypot", jnp.hypot)


def pow(x, y, name=None):  # noqa: A001 - paddle API name
    return pow_(x, y)


# ---- elementwise unary (reference: operators/activation_op.cc etc.) -----
neg = _unary("neg", jnp.negative)
abs = _unary("abs", jnp.abs)  # noqa: A001
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", lax.rsqrt)
square = _unary("square", jnp.square)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)  # noqa: A001
trunc = _unary("trunc", jnp.trunc)
sign = _unary("sign", jnp.sign)
reciprocal = _unary("reciprocal", jnp.reciprocal)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
digamma = _unary("digamma", jax.scipy.special.digamma)
frac = _unary("frac", lambda x: x - jnp.trunc(x))
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)

isnan = _unary("isnan", jnp.isnan)
isinf = _unary("isinf", jnp.isinf)
isfinite = _unary("isfinite", jnp.isfinite)


@primitive(name="clip")
def _clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


def clip(x, min=None, max=None, name=None):
    min = float(min) if isinstance(min, Tensor) else min
    max = float(max) if isinstance(max, Tensor) else max
    return _clip(ensure_tensor(x), min=min, max=max)


@primitive(name="scale")
def _scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    """reference: operators/scale_op.cc"""
    out = _scale(ensure_tensor(x),
                 scale=float(scale) if not isinstance(scale, Tensor)
                 else scale.item(),
                 bias=float(bias), bias_after_scale=bias_after_scale)
    return out


@primitive(name="lerp")
def _lerp(x, y, w):
    return x + w * (y - x)


def lerp(x, y, weight, name=None):
    if not isinstance(weight, Tensor):
        weight = Tensor(jnp.asarray(weight, x._data.dtype))
    return _lerp(x, y, weight)


@primitive(name="stanh")
def _stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _stanh(ensure_tensor(x), scale_a=scale_a, scale_b=scale_b)


# ---- reductions (reference: operators/reduce_ops/) ----------------------
def _reduce(name, fn, arg_dtype=None):
    prim = primitive(name=name)(fn)

    def op(x, axis=None, keepdim=False, name=None):
        x = ensure_tensor(x)
        if isinstance(axis, (list, tuple)):
            axis = tuple(int(a) for a in axis)
        elif axis is not None and not isinstance(axis, int):
            axis = int(axis)
        return prim(x, axis=axis, keepdims=keepdim)

    op.__name__ = name
    return op


sum = _reduce("reduce_sum", jnp.sum)  # noqa: A001
mean = _reduce("reduce_mean", jnp.mean)
prod = _reduce("reduce_prod", jnp.prod)
max = _reduce("reduce_max", jnp.max)  # noqa: A001
min = _reduce("reduce_min", jnp.min)  # noqa: A001
amax = max
amin = min
all = _reduce("reduce_all", jnp.all)  # noqa: A001
any = _reduce("reduce_any", jnp.any)  # noqa: A001


def nansum(x, axis=None, keepdim=False, name=None):
    return primitive(name="nansum")(jnp.nansum)(
        ensure_tensor(x), axis=axis, keepdims=keepdim)


@primitive(name="logsumexp")
def _logsumexp(x, axis=None, keepdims=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims)


def logsumexp(x, axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _logsumexp(ensure_tensor(x), axis=axis, keepdims=keepdim)


@primitive(name="cumsum")
def _cumsum(x, axis=None):
    return jnp.cumsum(x, axis=axis)


def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        x = x.astype(dtype)
    return _cumsum(x, axis=axis)


@primitive(name="cumprod")
def _cumprod(x, axis=None):
    return jnp.cumprod(x, axis=axis)


def cumprod(x, dim=None, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        x = x.astype(dtype)
    return _cumprod(x, axis=dim)


# ---- matmul family (reference: matmul_v2_op.cc, mul_op.cc, bmm_op.cc) ---
@primitive(name="matmul_v2")
def _matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    # bf16 inputs hit the MXU directly; fp32 uses default XLA precision
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _matmul(ensure_tensor(x), ensure_tensor(y),
                   transpose_x=transpose_x, transpose_y=transpose_y)


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return matmul(x, y)


dot_ = primitive(name="dot")(
    lambda x, y: jnp.sum(x * y, axis=-1))


def dot(x, y, name=None):
    return dot_(ensure_tensor(x), ensure_tensor(y))


@primitive(name="addmm")
def _addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _addmm(ensure_tensor(input), ensure_tensor(x), ensure_tensor(y),
                  beta=float(beta), alpha=float(alpha))


@primitive(name="outer")
def _outer(x, y):
    return jnp.outer(x, y)


def outer(x, y, name=None):
    return _outer(ensure_tensor(x), ensure_tensor(y))


def mv(x, vec, name=None):
    return matmul(x, vec)


@primitive(name="multiply_sum", )
def _inner(x, y):
    return jnp.inner(x, y)


def inner(x, y, name=None):
    return _inner(ensure_tensor(x), ensure_tensor(y))


# ---- comparison (reference: operators/controlflow/compare_op.cc) --------
equal = _binary("equal", jnp.equal)
not_equal = _binary("not_equal", jnp.not_equal)
greater_than = _binary("greater_than", jnp.greater)
greater_equal = _binary("greater_equal", jnp.greater_equal)
less_than = _binary("less_than", jnp.less)
less_equal = _binary("less_equal", jnp.less_equal)


def equal_all(x, y, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if tuple(x.shape) != tuple(y.shape):
        return Tensor(jnp.asarray(False))
    return primitive(name="equal_all")(
        lambda a, b: jnp.all(a == b))(x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return primitive(name="allclose")(
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                  equal_nan=equal_nan))(
        ensure_tensor(x), ensure_tensor(y))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return primitive(name="isclose")(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                 equal_nan=equal_nan))(
        ensure_tensor(x), ensure_tensor(y))


# ---- logical ------------------------------------------------------------
logical_and = _binary("logical_and", jnp.logical_and)
logical_or = _binary("logical_or", jnp.logical_or)
logical_xor = _binary("logical_xor", jnp.logical_xor)
logical_not = _unary("logical_not", jnp.logical_not)
bitwise_and = _binary("bitwise_and", jnp.bitwise_and)
bitwise_or = _binary("bitwise_or", jnp.bitwise_or)
bitwise_xor = _binary("bitwise_xor", jnp.bitwise_xor)
bitwise_not = _unary("bitwise_not", jnp.bitwise_not)


# ---- stat ---------------------------------------------------------------
def _correction_reduce(name, fn):
    prim = primitive(name=name)(fn)

    def op(x, axis=None, unbiased=True, keepdim=False, name=None):
        if isinstance(axis, (list, tuple)):
            axis = tuple(axis)
        return prim(ensure_tensor(x), axis=axis,
                    ddof=1 if unbiased else 0, keepdims=keepdim)

    op.__name__ = name
    return op


var = _correction_reduce("reduce_var", jnp.var)
std = _correction_reduce("reduce_std", jnp.std)


@primitive(name="median")
def _median(x, axis=None, keepdims=False):
    return jnp.median(x, axis=axis, keepdims=keepdims)


def median(x, axis=None, keepdim=False, name=None):
    return _median(ensure_tensor(x), axis=axis, keepdims=keepdim)


@primitive(name="quantile")
def _quantile(x, q, axis=None, keepdims=False):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdims)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return _quantile(ensure_tensor(x), q, axis=axis, keepdims=keepdim)


def numel(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x).size, jnp.int64))


@primitive(name="trace")
def _trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _trace(ensure_tensor(x), offset=offset, axis1=axis1, axis2=axis2)


def increment(x, value=1.0, name=None):
    """reference: operators/increment_op.cc — in-place add of a scalar."""
    x._data = x._data + jnp.asarray(value, x._data.dtype)
    return x


def multiplex(inputs, index, name=None):
    """reference: operators/multiplex_op.cc"""
    stacked = jnp.stack([ensure_tensor(t)._data for t in inputs])
    idx = ensure_tensor(index)._data.reshape(-1)
    rows = jnp.arange(stacked.shape[1])
    return Tensor(stacked[idx, rows[:idx.shape[0]]])
