"""Linear algebra ops (reference: operators/norm_op.cc, p_norm_op.cc,
cholesky_op.cc, svd helpers in math/, paddle.linalg namespace)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import primitive, ensure_tensor


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)

    if p == "fro" or (p == 2 and axis is None):
        fn = lambda a: jnp.sqrt(jnp.sum(jnp.square(a), axis=axis,
                                        keepdims=keepdim))
    elif p == float("inf"):
        fn = lambda a: jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
    elif p == float("-inf"):
        fn = lambda a: jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
    elif p == 0:
        fn = lambda a: jnp.sum((a != 0).astype(a.dtype), axis=axis,
                               keepdims=keepdim)
    elif p == 1:
        fn = lambda a: jnp.sum(jnp.abs(a), axis=axis, keepdims=keepdim)
    else:
        pf = float(p)
        fn = lambda a: jnp.power(
            jnp.sum(jnp.power(jnp.abs(a), pf), axis=axis, keepdims=keepdim),
            1.0 / pf)
    return primitive(name="p_norm")(fn)(x)


def dist(x, y, p=2, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    diff = primitive(name="dist_sub")(jnp.subtract)(x, y)
    return norm(diff, p=p)


@primitive(name="cholesky")
def _cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


def cholesky(x, upper=False, name=None):
    return _cholesky(ensure_tensor(x), upper=upper)


@primitive(name="inverse")
def _inv(x):
    return jnp.linalg.inv(x)


def inverse(x, name=None):
    return _inv(ensure_tensor(x))


inv = inverse


@primitive(name="matrix_power")
def _matrix_power(x, n=1):
    return jnp.linalg.matrix_power(x, n)


def matrix_power(x, n, name=None):
    return _matrix_power(ensure_tensor(x), n=int(n))


def det(x, name=None):
    return primitive(name="determinant")(jnp.linalg.det)(ensure_tensor(x))


def slogdet(x, name=None):
    x = ensure_tensor(x)
    sign, logabs = jnp.linalg.slogdet(x._data)
    return Tensor(jnp.stack([sign, logabs]))


def svd(x, full_matrices=False, name=None):
    """x = U @ diag(S) @ VH (paddle.linalg.svd convention: the third
    output is VH, not V)."""
    x = ensure_tensor(x)
    u, s, vh = jnp.linalg.svd(x._data, full_matrices=full_matrices)
    return Tensor(u), Tensor(s), Tensor(vh)


def qr(x, mode="reduced", name=None):
    x = ensure_tensor(x)
    q, r = jnp.linalg.qr(x._data, mode=mode)
    return Tensor(q), Tensor(r)


def eigh(x, UPLO="L", name=None):
    x = ensure_tensor(x)
    w, v = jnp.linalg.eigh(x._data, symmetrize_input=True)
    return Tensor(w), Tensor(v)


def eigvalsh(x, UPLO="L", name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.linalg.eigvalsh(x._data))


@primitive(name="solve")
def _solve(a, b):
    return jnp.linalg.solve(a, b)


def solve(x, y, name=None):
    return _solve(ensure_tensor(x), ensure_tensor(y))


@primitive(name="triangular_solve")
def _triangular_solve(a, b, upper=True, transpose=False, unitriangular=False):
    import jax.scipy.linalg as jsl
    return jsl.solve_triangular(a, b, lower=not upper,
                                trans=1 if transpose else 0,
                                unit_diagonal=unitriangular)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return _triangular_solve(ensure_tensor(x), ensure_tensor(y), upper=upper,
                             transpose=transpose,
                             unitriangular=unitriangular)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.linalg.matrix_rank(x._data, rtol=tol).astype("int64"))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.linalg.pinv(x._data, rtol=rcond, hermitian=hermitian))


def cond(x, p=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.linalg.cond(x._data, p=p))


def multi_dot(tensors, name=None):
    arrays = [ensure_tensor(t) for t in tensors]
    prim = primitive(name="multi_dot")(
        lambda *arrs: jnp.linalg.multi_dot(arrs))
    return prim(*arrays)


def cross(x, y, axis=9, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    if axis == 9:  # paddle default: first axis with dim 3
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    prim = primitive(name="cross")(
        lambda a, b: jnp.cross(a, b, axis=axis))
    return prim(x, y)


def histogram(x, bins=100, min=0, max=0, name=None):
    import numpy as np
    x = ensure_tensor(x)
    arr = np.asarray(x._data)
    if min == 0 and max == 0:
        min, max = float(arr.min()), float(arr.max())
    hist, _ = np.histogram(arr, bins=bins, range=(min, max))
    return Tensor(hist.astype(np.int64))


def bincount(x, weights=None, minlength=0, name=None):
    x = ensure_tensor(x)
    w = ensure_tensor(weights)._data if weights is not None else None
    return Tensor(jnp.bincount(x._data.reshape(-1), weights=w,
                               minlength=int(minlength),
                               length=None))


def lstsq(x, y, rcond=None, driver=None, name=None):
    """paddle.linalg.lstsq — least-squares solution (reference lstsq_op).

    Returns (solution, residuals, rank, singular_values) like paddle 2.x.
    Accepts batched (*, M, N) inputs via vmap over the leading dims; the
    `driver` knob is a LAPACK-backend selector with no XLA analogue and is
    ignored.
    """
    x, y = ensure_tensor(x), ensure_tensor(y)
    a, b = x._data, y._data
    solver = lambda ai, bi: jnp.linalg.lstsq(ai, bi, rcond=rcond)
    for _ in range(a.ndim - 2):
        solver = jax.vmap(solver)
    sol, res, rank, sv = solver(a, b)
    return (Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv))


def lu(x, pivot=True, get_infos=False, name=None):
    """paddle.linalg.lu — LU factorization (packed LU + pivots).

    Pivots are 1-based (paddle convention: 1 <= pivots[i] <= m); infos[i]>0
    flags a zero pivot on the diagonal (singular factorization).
    """
    import jax.scipy.linalg as jsl
    x = ensure_tensor(x)
    lu_mat, piv = jsl.lu_factor(x._data)
    piv = piv + 1
    if get_infos:
        diag = jnp.diagonal(lu_mat, axis1=-2, axis2=-1)
        zero = diag == 0
        # first zero-pivot index + 1, or 0 when none (LAPACK getrf contract)
        first = jnp.argmax(zero, axis=-1) + 1
        info = jnp.where(jnp.any(zero, axis=-1), first, 0).astype(jnp.int32)
        return Tensor(lu_mat), Tensor(piv), Tensor(info)
    return Tensor(lu_mat), Tensor(piv)


def _complex_of(dt):
    return jnp.complex128 if dt == jnp.float64 else jnp.complex64


def eig(x, name=None):
    """paddle.linalg.eig — general eigendecomposition.  XLA has no TPU
    lowering for nonsymmetric eig (the reference's eig_op is CPU-only too):
    eager calls run numpy on host; traced calls go through jax.pure_callback
    (supported on the CPU backend; the axon TPU plugin lacks host callbacks,
    so keep eig outside jit there)."""
    import numpy as np
    x = ensure_tensor(x)
    a = x._data
    cdt = _complex_of(a.dtype)

    def host_eig(m):
        w, v = np.linalg.eig(np.asarray(m))
        return w.astype(cdt), v.astype(cdt)

    if isinstance(a, jax.core.Tracer):
        w_shape = jax.ShapeDtypeStruct(a.shape[:-1], cdt)
        v_shape = jax.ShapeDtypeStruct(a.shape, cdt)
        w, v = jax.pure_callback(host_eig, (w_shape, v_shape), a)
    else:
        # complex results stay on CPU: the axon TPU backend can't hold
        # complex dtypes (readback would raise UNIMPLEMENTED)
        cpu = jax.devices("cpu")[0]
        w, v = host_eig(a)
        w, v = jax.device_put(w, cpu), jax.device_put(v, cpu)
    return Tensor(w), Tensor(v)


def eigvals(x, name=None):
    import numpy as np
    x = ensure_tensor(x)
    a = x._data
    cdt = _complex_of(a.dtype)
    host = lambda m: np.linalg.eigvals(np.asarray(m)).astype(cdt)
    if isinstance(a, jax.core.Tracer):
        w = jax.pure_callback(
            host, jax.ShapeDtypeStruct(a.shape[:-1], cdt), a)
    else:
        w = jax.device_put(host(a), jax.devices("cpu")[0])
    return Tensor(w)
