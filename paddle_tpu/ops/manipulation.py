"""Shape / indexing / rearrangement ops.

Reference parity: reshape_op.cc, transpose_op.cc, squeeze_op.cc, concat_op.cc,
split_op.cc, stack_op.cc, gather(_nd)_op.cc, scatter_op.cc, slice_op.cc,
tile_op.cc, expand_v2_op.cc, flip_op.cc, roll_op.cc, where_op.cc,
index_select_op.cc, top_k_v2_op.cc, argsort_op.cc, unique_op.cc,
shard_index_op.cc, cast_op.cc.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor
from ..core.dispatch import primitive, ensure_tensor
from ..core import dtype as dtypes


@primitive(name="cast")
def _cast(x, dtype=None):
    return x.astype(dtype)


def cast(x, dtype):
    x = ensure_tensor(x)
    jdt = dtypes.to_jax(dtype)
    if x._data.dtype == jdt:
        return x
    return _cast(x, dtype=jdt)


@primitive(name="reshape")
def _reshape(x, shape=None):
    return jnp.reshape(x, shape)


def _dim(s):
    """One reshape dim: Tensor -> concrete int; plain numbers -> int;
    anything else (jax shape-poly symbolic dims under `jax.export` with
    dynamic batch) passes through for jnp to consume — forcing int()
    would break dynamic-dim export of the common
    ``x.reshape([x.shape[0], -1])`` pattern."""
    if isinstance(s, Tensor):
        return s.item()
    try:
        return int(s)
    except Exception:
        # symbolic dims raise InconclusiveDimensionOperation from
        # __int__; jnp.reshape validates whatever passes through
        return s


def reshape(x, shape, name=None):
    x = ensure_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = tuple(_dim(s) for s in shape)
    return _reshape(x, shape=shape)


def reshape_(x, shape, name=None):
    from ..core.autograd import run_inplace
    return run_inplace(x, reshape, shape)


@primitive(name="transpose")
def _transpose(x, perm=None):
    return jnp.transpose(x, perm)


def transpose(x, perm=None, name=None):
    return _transpose(ensure_tensor(x),
                      perm=tuple(perm) if perm is not None else None)


def t(x, name=None):
    x = ensure_tensor(x)
    if x.ndim < 2:
        return x
    return transpose(x, list(range(x.ndim - 2)) + [x.ndim - 1, x.ndim - 2])


def moveaxis(x, source, destination, name=None):
    prim = primitive(name="moveaxis")(
        lambda a: jnp.moveaxis(a, source, destination))
    return prim(ensure_tensor(x))


def swapaxes(x, axis1, axis2, name=None):
    x = ensure_tensor(x)
    perm = list(range(x.ndim))
    perm[axis1], perm[axis2] = perm[axis2], perm[axis1]
    return transpose(x, perm)


@primitive(name="squeeze")
def _squeeze(x, axis=None):
    return jnp.squeeze(x, axis)


def squeeze(x, axis=None, name=None):
    x = ensure_tensor(x)
    if isinstance(axis, (list, tuple)):
        axis = tuple(a for a in axis if x.shape[a] == 1)
        if not axis:
            axis = None
    elif isinstance(axis, int) and x.shape[axis] != 1:
        return x
    return _squeeze(x, axis=axis)


@primitive(name="unsqueeze")
def _unsqueeze(x, axis=None):
    return jnp.expand_dims(x, axis)


def unsqueeze(x, axis, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _unsqueeze(ensure_tensor(x), axis=axis)


@primitive(name="flatten")
def _flatten(x, start_axis=0, stop_axis=-1):
    shape = x.shape
    stop = stop_axis % x.ndim if x.ndim else 0
    start = start_axis % x.ndim if x.ndim else 0
    new_shape = shape[:start] + (-1,) + shape[stop + 1:]
    return jnp.reshape(x, new_shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    if x.ndim == 0:
        return reshape(x, [1])
    return _flatten(x, start_axis=start_axis, stop_axis=stop_axis)


def concat(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    prim = primitive(name="concat")(
        lambda *arrs: jnp.concatenate(arrs, axis=axis))
    return prim(*tensors)


def stack(x, axis=0, name=None):
    tensors = [ensure_tensor(t) for t in x]
    prim = primitive(name="stack")(
        lambda *arrs: jnp.stack(arrs, axis=axis))
    return prim(*tensors)


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dim {dim} on axis {axis} is not divisible by "
                f"num_or_sections={num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) if not isinstance(s, Tensor) else int(s.item())
                 for s in num_or_sections]
        n_neg = builtins_sum(1 for s in sizes if s < 0)
        if n_neg:
            rest = dim - builtins_sum(s for s in sizes if s >= 0)
            sizes = [rest if s < 0 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    prim = primitive(name="split")(
        lambda a: tuple(
            lax.slice_in_dim(a, o, o + s, axis=axis)
            for o, s in zip(offsets, sizes)))
    out = prim(x)
    return list(out) if isinstance(out, tuple) else [out]


builtins_sum = sum


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    x = ensure_tensor(x)
    outs = split(x, x.shape[axis], axis)
    return [squeeze(o, axis) for o in outs]


@primitive(name="tile")
def _tile(x, repeat_times=None):
    return jnp.tile(x, repeat_times)


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()
    return _tile(ensure_tensor(x), repeat_times=tuple(int(r)
                                                      for r in repeat_times))


@primitive(name="expand_v2")
def _expand(x, shape=None):
    return jnp.broadcast_to(x, shape)


def expand(x, shape, name=None):
    x = ensure_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = list(int(s) for s in shape)
    # -1 means keep the original extent
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1:
            shape[i] = x.shape[i - offset]
    return _expand(x, shape=tuple(shape))


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return expand(x, ensure_tensor(y).shape)


def broadcast_tensors(inputs, name=None):
    arrays = [ensure_tensor(t)._data for t in inputs]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrays])
    return [expand(ensure_tensor(t), shape) for t in inputs]


@primitive(name="flip")
def _flip(x, axis=None):
    return jnp.flip(x, axis)


def flip(x, axis, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _flip(ensure_tensor(x), axis=axis)


@primitive(name="roll")
def _roll(x, shifts=None, axis=None):
    return jnp.roll(x, shifts, axis)


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, (list, tuple)):
        shifts = tuple(shifts)
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return _roll(ensure_tensor(x), shifts=shifts, axis=axis)


@primitive(name="rot90")
def _rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


def rot90(x, k=1, axes=(0, 1), name=None):
    return _rot90(ensure_tensor(x), k=k, axes=tuple(axes))


# ---- gather / scatter ----------------------------------------------------
@primitive(name="gather", nondiff=(1,))
def _gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    index = ensure_tensor(index)
    idx = index._data
    if idx.ndim == 2 and idx.shape[1] == 1:
        idx = idx[:, 0]
    return _gather(ensure_tensor(x), Tensor(idx), axis=axis)


@primitive(name="gather_nd", nondiff=(1,))
def _gather_nd(x, index):
    # index: [..., k] indexes first k dims of x
    k = index.shape[-1]
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def gather_nd(x, index, name=None):
    return _gather_nd(ensure_tensor(x), ensure_tensor(index))


@primitive(name="scatter", nondiff=(1,))
def _scatter(x, index, updates, overwrite=True):
    if index.ndim == 2 and index.shape[1] == 1:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates)
    # paddle semantics: zero the rows then accumulate
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter(x, index, updates, overwrite=True, name=None):
    return _scatter(ensure_tensor(x), ensure_tensor(index),
                    ensure_tensor(updates), overwrite=overwrite)


@primitive(name="scatter_nd_add", nondiff=(1,))
def _scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    return _scatter_nd_add(ensure_tensor(x), ensure_tensor(index),
                           ensure_tensor(updates))


def scatter_nd(index, updates, shape, name=None):
    updates = ensure_tensor(updates)
    zeros = Tensor(jnp.zeros(tuple(shape), updates._data.dtype))
    return scatter_nd_add(zeros, index, updates)


@primitive(name="index_select", nondiff=(1,))
def _index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def index_select(x, index, axis=0, name=None):
    return _index_select(ensure_tensor(x), ensure_tensor(index), axis=axis)


@primitive(name="index_sample", nondiff=(1,))
def _index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def index_sample(x, index, name=None):
    return _index_sample(ensure_tensor(x), ensure_tensor(index))


@primitive(name="take_along_axis", nondiff=(1,))
def _take_along_axis(x, index, axis):
    return jnp.take_along_axis(x, index, axis=axis)


def take_along_axis(arr, indices, axis, name=None):
    return _take_along_axis(ensure_tensor(arr), ensure_tensor(indices),
                            axis=axis)


@primitive(name="put_along_axis", nondiff=(1,))
def _put_along_axis(x, index, value, axis, reduce="assign"):
    if reduce == "add":
        return jnp.put_along_axis(x, index, value, axis=axis,
                                  inplace=False, mode="add") \
            if hasattr(jnp, "put_along_axis") else _pal_add(x, index, value,
                                                            axis)
    return jnp.put_along_axis(x, index, value, axis=axis, inplace=False) \
        if hasattr(jnp, "put_along_axis") else _pal_set(x, index, value, axis)


def _pal_set(x, index, value, axis):
    idx = jnp.meshgrid(*[jnp.arange(s) for s in index.shape], indexing="ij")
    idx[axis] = index
    return x.at[tuple(idx)].set(jnp.broadcast_to(value, index.shape))


def _pal_add(x, index, value, axis):
    idx = jnp.meshgrid(*[jnp.arange(s) for s in index.shape], indexing="ij")
    idx[axis] = index
    return x.at[tuple(idx)].add(jnp.broadcast_to(value, index.shape))


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    return _put_along_axis(ensure_tensor(arr), ensure_tensor(indices),
                           ensure_tensor(values)._data, axis=axis,
                           reduce=reduce)


@primitive(name="where")
def _where(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None, name=None):
    condition = ensure_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return _where(condition, ensure_tensor(x, ref=y),
                  ensure_tensor(y, ref=x))


def masked_select(x, mask, name=None):
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    # dynamic output shape: eager-only (host round trip), like reference LoD
    data = np.asarray(x._data)[np.asarray(mask._data)]
    return Tensor(data)


def masked_fill(x, mask, value, name=None):
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    v = value._data if isinstance(value, Tensor) else value
    return primitive(name="masked_fill")(
        lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a))(x, mask)


def nonzero(x, as_tuple=False, name=None):
    x = ensure_tensor(x)
    idx = np.nonzero(np.asarray(x._data))
    if as_tuple:
        return tuple(Tensor(i.reshape(-1, 1).astype(np.int64)) for i in idx)
    return Tensor(np.stack(idx, axis=1).astype(np.int64))


# ---- search / sort -------------------------------------------------------
@primitive(name="argmax")
def _argmax(x, axis=None, keepdims=False):
    return jnp.argmax(x, axis=axis, keepdims=keepdims)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = _argmax(ensure_tensor(x), axis=axis, keepdims=keepdim)
    return cast(out, dtype)


@primitive(name="argmin")
def _argmin(x, axis=None, keepdims=False):
    return jnp.argmin(x, axis=axis, keepdims=keepdims)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = _argmin(ensure_tensor(x), axis=axis, keepdims=keepdim)
    return cast(out, dtype)


@primitive(name="argsort")
def _argsort(x, axis=-1, descending=False):
    order = jnp.argsort(x, axis=axis, descending=descending)
    return order


def argsort(x, axis=-1, descending=False, name=None):
    return cast(_argsort(ensure_tensor(x), axis=axis, descending=descending),
                "int64")


@primitive(name="sort")
def _sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis, descending=descending)
    return out


def sort(x, axis=-1, descending=False, name=None):
    return _sort(ensure_tensor(x), axis=axis, descending=descending)


@primitive(name="top_k_v2", has_aux=True)
def _topk(x, k=1, largest=True):
    if largest:
        vals, idx = lax.top_k(x, k)
    else:
        vals, idx = lax.top_k(-x, k)
        vals = -vals
    return vals, idx.astype(jnp.int64)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())
    if axis is not None and axis % x.ndim != x.ndim - 1:
        xs = swapaxes(x, axis, -1)
        vals, idx = _topk(xs, k=k, largest=largest)
        return swapaxes(vals, axis, -1), swapaxes(idx, axis, -1)
    return _topk(x, k=k, largest=largest)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    sorted_t = sort(x, axis=axis)
    idx_t = argsort(x, axis=axis)
    sel = [slice(None)] * x.ndim
    sel[axis] = int(k) - 1
    v = sorted_t[tuple(sel)]
    i = idx_t[tuple(sel)]
    if keepdim:
        v, i = unsqueeze(v, axis), unsqueeze(i, axis)
    return v, i


def mode(x, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    arr = np.asarray(x._data)
    sorted_arr = np.sort(arr, axis=axis)
    moved = np.moveaxis(sorted_arr, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], dtype=arr.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    orig = np.moveaxis(arr, axis, -1).reshape(-1, moved.shape[-1])
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        # paddle keeps the LAST-occurring max-count value's index
        best = uniq[np.argmax(counts)]
        vals[i] = best
        idxs[i] = np.where(orig[i] == best)[0][-1]
    out_shape = list(moved.shape[:-1])
    v = Tensor(vals.reshape(out_shape))
    i_t = Tensor(idxs.reshape(out_shape))
    if keepdim:
        v, i_t = unsqueeze(v, axis), unsqueeze(i_t, axis)
    return v, i_t


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    res = np.unique(np.asarray(x._data), return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(res)
    outs = [Tensor(res[0])]
    for extra in res[1:]:
        outs.append(Tensor(extra.astype(np.int64)))
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    arr = np.asarray(x._data)
    if axis is None:
        arr = arr.reshape(-1)
        n = arr.size
        change = np.concatenate([[True], arr[1:] != arr[:-1]]) \
            if n else np.zeros((0,), bool)
        out = arr[change]
    else:
        # axis case: consecutive-duplicate SLICES along axis collapse
        moved = np.moveaxis(arr, axis, 0)
        n = moved.shape[0]
        if n:
            flat = moved.reshape(n, -1)
            change = np.concatenate(
                [[True], np.any(flat[1:] != flat[:-1], axis=1)])
        else:
            change = np.zeros((0,), bool)
        out = np.moveaxis(moved[change], 0, axis)
    outs = [Tensor(out)]
    if return_inverse:
        outs.append(Tensor(np.cumsum(change).astype(np.int64) - 1))
    if return_counts:
        idx = np.flatnonzero(change)
        counts = np.diff(np.concatenate([idx, [n]]))
        outs.append(Tensor(counts.astype(np.int64)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    ss, v = ensure_tensor(sorted_sequence), ensure_tensor(values)
    side = "right" if right else "left"
    out = jnp.searchsorted(ss._data, v._data, side=side)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


# ---- misc ---------------------------------------------------------------
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """reference: operators/shard_index_op.cc (used by parallel embedding)."""
    input = ensure_tensor(input)
    shard_size = (index_num + nshards - 1) // nshards
    arr = input._data
    in_shard = (arr // shard_size) == shard_id
    out = jnp.where(in_shard, arr % shard_size, ignore_value)
    return Tensor(out)


def repeat_interleave(x, repeats, axis=None, name=None):
    x = ensure_tensor(x)
    if isinstance(repeats, Tensor):
        repeats = repeats.numpy()
        total = int(repeats.sum())
        return Tensor(jnp.repeat(x._data, jnp.asarray(repeats), axis=axis,
                                 total_repeat_length=total))
    prim = primitive(name="repeat_interleave")(
        lambda a: jnp.repeat(a, repeats, axis=axis))
    return prim(x)


def as_real(x, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.stack([jnp.real(x._data), jnp.imag(x._data)], axis=-1))


def as_complex(x, name=None):
    x = ensure_tensor(x)
    return Tensor(lax.complex(x._data[..., 0], x._data[..., 1]))


def tensordot(x, y, axes=2, name=None):
    prim = primitive(name="tensordot")(
        lambda a, b: jnp.tensordot(a, b, axes=axes))
    return prim(ensure_tensor(x), ensure_tensor(y))


def einsum(equation, *operands):
    tensors = [ensure_tensor(t) for t in operands]
    prim = primitive(name="einsum")(
        lambda *arrs: jnp.einsum(equation, *arrs))
    return prim(*tensors)


def one_hot(x, num_classes, name=None):
    x = ensure_tensor(x)
    return Tensor(jax.nn.one_hot(x._data, num_classes,
                                 dtype=dtypes.to_jax(
                                     dtypes.get_default_dtype())))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    x = ensure_tensor(x)
    pre = ensure_tensor(prepend)._data if prepend is not None else None
    app = ensure_tensor(append)._data if append is not None else None
    prim = primitive(name="diff")(
        lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app))
    return prim(x)
