"""Legacy / 1.x-style API names kept at the paddle top level.

Reference parity: the alias block of ``python/paddle/__init__.py``
(DEFINE_ALIAS entries) plus fluid-era layers that survived into 2.0:
``elementwise_*`` / ``reduce_*`` (fluid/layers/nn.py), ``fill_constant`` /
``create_global_var`` / ``create_parameter`` (fluid/layers/tensor.py),
``has_inf/has_nan/isfinite`` (fluid/layers/ops), in-place variants
(``tanh_`` etc., dygraph inplace API), ``set_printoptions``.
"""
from __future__ import annotations

import builtins

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core.dispatch import primitive, ensure_tensor
from ..core import dtype as dtypes


# ---- aggregation / shape helpers -----------------------------------------

@primitive(name="add_n")
def _add_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def add_n(inputs, name=None):
    """reference: sum_op.cc (paddle.add_n)."""
    if isinstance(inputs, Tensor):
        return inputs
    return _add_n(*[ensure_tensor(x) for x in inputs])


@primitive(name="kron")
def _kron(a, b):
    return jnp.kron(a, b)


def kron(x, y, name=None):
    return _kron(ensure_tensor(x), ensure_tensor(y))


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def rank(input):
    return ensure_tensor(input).ndim


def shape(input):
    """reference shape_op: returns the shape as a 1-D int32 tensor."""
    return Tensor(np.asarray(ensure_tensor(input).shape, np.int32))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor(np.asarray(ensure_tensor(x).size == 0))


def unstack(x, axis=0, num=None, name=None):
    x = ensure_tensor(x)
    n = num or x.shape[axis]
    from .manipulation import split, squeeze
    parts = split(x, n, axis=axis)
    return [squeeze(p, axis=axis) for p in parts]


def slice(input, axes, starts, ends):
    """reference slice_op.cc."""
    x = ensure_tensor(input)
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = builtins.slice(int(s), int(e))
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(int(s), int(e), int(st))
    return x[tuple(idx)]


def crop_tensor(x, shape=None, offsets=None, name=None):
    x = ensure_tensor(x)
    offsets = offsets or [0] * x.ndim
    idx = tuple(builtins.slice(int(o), int(o) + int(s))
                for o, s in zip(offsets, shape))
    return x[idx]


# ---- fluid-era creation ---------------------------------------------------

def fill_constant(shape, dtype, value, name=None, out=None):
    from .creation import full
    res = full(shape, value, dtype=dtype)
    if out is not None:
        out.set_value(res)
        return out
    return res


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    t = Tensor(np.full(shape, value, dtypes.to_numpy(dtype)
                       if hasattr(dtypes, "to_numpy") else dtype), name=name)
    t.persistable = persistable
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..nn import initializer as I
    init = default_initializer or (
        I.Constant(0.0) if is_bias else I.XavierUniform())
    return Parameter(init(shape, dtype), name=name)


# ---- numeric checks -------------------------------------------------------

@primitive(name="has_inf")
def _has_inf(x):
    return jnp.isinf(x).any()


@primitive(name="has_nan")
def _has_nan(x):
    return jnp.isnan(x).any()


def has_inf(x):
    return _has_inf(ensure_tensor(x))


def has_nan(x):
    return _has_nan(ensure_tensor(x))


# ---- elementwise_* / reduce_* legacy names -------------------------------

def _elementwise(op_name):
    def op(x, y, axis=-1, act=None, name=None):
        from . import math as M
        fn = getattr(M, op_name)
        out = fn(ensure_tensor(x), ensure_tensor(y))
        if act:
            from ..nn import functional as F
            out = getattr(F, act)(out)
        return out
    op.__name__ = "elementwise_" + op_name
    return op


elementwise_add = _elementwise("add")
elementwise_sub = _elementwise("subtract")
elementwise_mul = _elementwise("multiply")
elementwise_div = _elementwise("divide")
elementwise_pow = _elementwise("pow")
elementwise_mod = _elementwise("mod")
elementwise_floordiv = _elementwise("floor_divide")
elementwise_max = _elementwise("maximum")
elementwise_min = _elementwise("minimum")


def _reduce(op_name):
    def op(input, dim=None, keep_dim=False, name=None):
        from . import math as M
        return getattr(M, op_name)(ensure_tensor(input), axis=dim,
                                   keepdim=keep_dim)
    op.__name__ = "reduce_" + op_name
    return op


reduce_sum = _reduce("sum")
reduce_mean = _reduce("mean")
reduce_max = _reduce("max")
reduce_min = _reduce("min")
reduce_prod = _reduce("prod")


# ---- in-place variants (dygraph inplace API) ------------------------------

def _inplace(fn_name, fn=None):
    """Build an in-place variant that keeps the autograd chain intact:
    the op consumes a snapshot of x's graph identity and x adopts the
    result's node (core/autograd.py snapshot_for_inplace/adopt_result),
    so backward applies the op's VJP instead of an identity."""
    def op(x, *args, **kwargs):
        from .. import ops as O
        from ..core import autograd
        from ..core.dispatch import ensure_tensor
        x = ensure_tensor(x)
        f = fn or getattr(O, fn_name)
        old = autograd.snapshot_for_inplace(x)
        res = f(old, *args, **kwargs)
        autograd.adopt_result(x, res)
        return x
    op.__name__ = fn_name + "_" if fn is None else fn_name
    return op


tanh_ = _inplace("tanh")
squeeze_ = _inplace("squeeze")
unsqueeze_ = _inplace("unsqueeze")
scatter_ = _inplace("scatter")
exp_ = _inplace("exp")
sqrt_ = _inplace("sqrt")
ceil_ = _inplace("ceil")
floor_ = _inplace("floor")
round_ = _inplace("round")
clip_ = _inplace("clip")
subtract_ = _inplace("subtract")
add_ = _inplace("add")


# ---- printing -------------------------------------------------------------

def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """paddle.set_printoptions → numpy printoptions (Tensor repr uses
    np.array2string)."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


# ---- LoDTensorArray ops (reference: fluid/layers/control_flow.py) ---------

def create_array(dtype="float32", initialized_list=None):
    """reference: create_array — dygraph uses a plain list."""
    return list(initialized_list or [])


def array_write(x, i, array=None):
    i = int(i)
    if array is None:
        array = []
    while len(array) <= i:
        array.append(None)
    array[i] = ensure_tensor(x)
    return array


def array_read(array, i):
    return array[int(i)]


def array_length(array):
    return Tensor(np.asarray(len(array), np.int64))
