"""Ragged paged attention — ONE Pallas kernel for every paged window.

The serving engine's paged attention used to be pure-XLA gather /
scatter through block tables, with the window width baked into each
compiled program's SHAPE: a one-token decode tick (S=1), a k-wide
speculative verify (S=k+1), and a chunked-prefill window (S=C) each
compiled their own executable, so the engine carried a program matrix
of roughly one entry per (layout, chunk shape, spec_k).  This module
is the kernel-level fix, grounded in "Ragged Paged Attention: A
High-Performance and Flexible LLM Inference Kernel for TPU"
(PAPERS.md, arxiv 2604.15464): per-slot positions, window widths, and
block tables become kernel *data* instead of trace-time *shape* —

* the grid runs over SLOTS; each program instance walks its slot's
  block table (a kv-block loop inside the instance) to gather the
  slot's logical K/V row from the shared physical pools,
* ``pos[b]`` (the slot's window start) drives the causal mask, so a
  short slot is masked by its length instead of padded to the pool's,
* ``width[b]`` says how many of the W query lanes are REAL this tick —
  a decode lane uses 1, a spec-verify lane k+1, a prefill-chunk lane
  its chunk length, and a parked slot 0 (its output lanes are zeroed,
  never read) — so mixed prefill-chunk + decode + spec traffic shares
  ONE program whose static width is just the engine's maximum.

Numerics are the XLA oracle's, on purpose: the kernel gathers the
whole logical row and runs the same f32 score -> -1e30 mask -> softmax
-> value contraction as ``GPTAttention._slot_attn``, so the engine's
token-parity guarantees (greedy AND seeded) carry over to the kernel
path — tier-1 runs this very kernel under ``interpret=True`` on CPU
and asserts token-for-token equality against the XLA path.  (A
flash-style online softmax over the kv-block loop would save VMEM on
long contexts but breaks bit-parity with the oracle; it belongs behind
the real-TPU tier of the ``pallas`` marker.)

K/V WRITES stay outside the kernel (the callers' width-masked scatter
— see ``GPTAttention.ragged_window_paged``): lanes past ``width[b]``
land in physical row 0, the engine's scratch block, which is how the
scratch-block and spec-margin invariants documented in
serving/kvcache.py move from per-path code into one masking rule.
"""
from __future__ import annotations

import math


def _auto_interpret():
    """Pallas interpret mode unless we are actually on TPU — tier-1
    (JAX_PLATFORMS=cpu) exercises the real kernel logic token-for-token
    against the XLA oracle; compiled Mosaic lowering is the TPU tier."""
    import jax
    return jax.default_backend() != "tpu"


def _ragged_paged_attention_impl(q, k_flat, v_flat, block_tables, pos,
                                 width, block_size, interpret,
                                 k_scale=None, v_scale=None):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B, W, H, hd = q.shape
    nb = block_tables.shape[1]
    bs = block_size
    L = nb * bs
    scale = 1.0 / math.sqrt(hd)
    quant = k_scale is not None

    def kernel(tables_ref, pos_ref, width_ref, q_ref, k_ref, v_ref,
               *rest):
        if quant:
            ks_ref, vs_ref, o_ref = rest
        else:
            (o_ref,) = rest
        b = pl.program_id(0)
        p = pos_ref[b]
        w = width_ref[b]

        def rows(pool_ref, scale_ref):
            # kv-block loop: gather this slot's logical [L] row
            # through its block table (physical block ids are runtime
            # data; nb/bs are the only static shapes).  Quantized
            # pools dequantize PER GATHERED BLOCK — int8 codes times
            # that block's per-head scale row, right here where the
            # block enters the contraction, never the whole pool.
            parts = []
            for j in range(nb):
                blk = pool_ref[pl.ds(tables_ref[b, j] * bs, bs)]
                if scale_ref is not None:
                    s = scale_ref[pl.ds(tables_ref[b, j], 1)][0]  # [H]
                    parts.append(blk.astype(jnp.float32)
                                 * s[None, :, None])
                else:
                    parts.append(blk)
            return jnp.concatenate(parts, axis=0)            # [L, H, hd]

        k_rows = rows(k_ref, ks_ref if quant else None)
        v_rows = rows(v_ref, vs_ref if quant else None)
        qa = q_ref[0].astype(jnp.float32)                    # [W, H, hd]
        # same contraction / mask / softmax as the XLA oracle
        # (_slot_attn), per slot: scores [H, W, L] in f32
        scores = jnp.einsum(
            "qhd,khd->hqk", qa,
            k_rows.astype(jnp.float32)) * scale
        l_ids = jax.lax.broadcasted_iota(jnp.int32, (W, L), 1)
        s_ids = jax.lax.broadcasted_iota(jnp.int32, (W, L), 0)
        # query lane s sees cache positions <= pos + s — the slot's
        # LENGTH does the masking, not a padded shape
        visible = l_ids <= p + s_ids                         # [W, L]
        scores = jnp.where(visible[None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("hqk,khd->qhd", probs,
                         v_rows.astype(jnp.float32))
        # width as data: lanes past this slot's real window are zeroed
        # (parked slots — width 0 — return all-zero, never-read lanes)
        lane = jax.lax.broadcasted_iota(jnp.int32, (W, 1, 1), 0)
        ctx = jnp.where(lane < w, ctx, 0.0)
        o_ref[0] = ctx.astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec(block_tables.shape, lambda b: (0, 0)),
        pl.BlockSpec(pos.shape, lambda b: (0,)),
        pl.BlockSpec(width.shape, lambda b: (0,)),
        pl.BlockSpec((1, W, H, hd), lambda b: (b, 0, 0, 0)),
        pl.BlockSpec(k_flat.shape, lambda b: (0, 0, 0)),
        pl.BlockSpec(v_flat.shape, lambda b: (0, 0, 0)),
    ]
    operands = [block_tables, pos, width, q, k_flat, v_flat]
    if quant:
        in_specs += [
            pl.BlockSpec(k_scale.shape, lambda b: (0, 0)),
            pl.BlockSpec(v_scale.shape, lambda b: (0, 0)),
        ]
        operands += [k_scale, v_scale]
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, W, H, hd), lambda b: (b, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, W, H, hd), q.dtype),
        interpret=interpret,
    )(*operands)


def ragged_paged_attention(q, k_flat, v_flat, block_tables, pos, width,
                           *, block_size, interpret=None,
                           k_scale=None, v_scale=None):
    """Ragged paged attention over a slot pool (see module docstring).

    q : [B, W, H, hd] query window per slot (W = the engine's static
        maximum window; real lanes per slot are ``width[b]``).
    k_flat / v_flat : [num_blocks * block_size, H, hd] — the paged
        pools flattened to physical rows (writes already scattered).
        With ``k_scale``/``v_scale`` these are int8 CODE rows.
    block_tables : int32 [B, L // block_size] physical block per
        logical block (row 0 = the scratch block for parked slots).
    pos : int32 [B] window start per slot (tokens already cached).
    width : int32 [B] real query lanes this tick (0 = parked; output
        lanes >= width are zeroed).
    k_scale / v_scale : optional f32 [num_blocks, H] per-block
        per-head dequant multipliers (``Engine(kv_dtype="int8")``):
        the kernel dequantizes each gathered block in-loop — codes
        times the block's scale row, adjacent to the contraction —
        so the logical K/V row never materializes outside VMEM and
        the whole pool is never dequantized.  Pass both or neither.
    Returns ctx [B, W, H, hd] in q's dtype.
    """
    import jax.numpy as jnp

    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            "ragged_paged_attention: pass both k_scale and v_scale "
            "(quantized pools) or neither (fp pools)")
    if interpret is None:
        interpret = _auto_interpret()
    if k_scale is not None:
        k_scale = jnp.asarray(k_scale, jnp.float32)
        v_scale = jnp.asarray(v_scale, jnp.float32)
    return _ragged_paged_attention_impl(
        q, k_flat, v_flat,
        jnp.asarray(block_tables, jnp.int32),
        jnp.asarray(pos, jnp.int32), jnp.asarray(width, jnp.int32),
        block_size=int(block_size), interpret=bool(interpret),
        k_scale=k_scale, v_scale=v_scale)
