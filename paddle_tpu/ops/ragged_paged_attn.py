"""Ragged paged attention — ONE Pallas kernel for every paged window.

The serving engine's paged attention used to be pure-XLA gather /
scatter through block tables, with the window width baked into each
compiled program's SHAPE: a one-token decode tick (S=1), a k-wide
speculative verify (S=k+1), and a chunked-prefill window (S=C) each
compiled their own executable, so the engine carried a program matrix
of roughly one entry per (layout, chunk shape, spec_k).  This module
is the kernel-level fix, grounded in "Ragged Paged Attention: A
High-Performance and Flexible LLM Inference Kernel for TPU"
(PAPERS.md, arxiv 2604.15464): per-slot positions, window widths, and
block tables become kernel *data* instead of trace-time *shape* —

* the grid runs over SLOTS; each program instance walks its slot's
  block table (a kv-block loop inside the instance) against the
  shared physical pools,
* ``pos[b]`` (the slot's window start) drives the causal mask, so a
  short slot is masked by its length instead of padded to the pool's,
* ``width[b]`` says how many of the W query lanes are REAL this tick —
  a decode lane uses 1, a spec-verify lane k+1, a prefill-chunk lane
  its chunk length, and a parked slot 0 (its output lanes are zeroed,
  never read) — so mixed prefill-chunk + decode + spec traffic shares
  ONE program whose static width is just the engine's maximum.

STREAMING (``variant="stream"``, the default): a flash-style
ONLINE-SOFTMAX loop.  K/V are consumed one paged block at a time
inside a ``fori_loop`` over the slot's LIVE blocks (the loop stops at
the causal horizon ``ceil((pos + width) / block_size)``, so a decode
tick touches only the blocks that actually hold history), carrying a
per-(head, lane) running max ``m``, normalizer ``l``, and an output
accumulator ``acc`` rescaled by ``exp(m_old - m_new)`` per block —
the standard flash-attention recurrence.  The per-slot working set is
therefore **O(block_size x window)** — one K block, one V block, one
[H, W, block_size] score tile, and the [W, H, hd] accumulator —
*independent of context length*, where the gather variant's is
O(context_len): multi-thousand-token contexts stop being VMEM-bounded
and the compiled program stays O(1) in size (the gather variant
unrolls a Python loop over ``L // block_size`` table entries, so its
trace/compile cost — and its concatenated [L, H, hd] row — grow
linearly with the context ceiling).

GATHER (``variant="gather"``, kept behind ``attn_impl=
"ragged_gather"`` for A/B): the original form — materialize the whole
logical [L, H, hd] row, then one monolithic f32 score -> -1e30 mask ->
softmax -> value contraction, BITWISE-equal to the XLA oracle
(``GPTAttention._slot_attn``) on CPU.

NUMERICS CONTRACT: online softmax reorders float summation (block-
sequential accumulation instead of one reduction over L), so the
streaming kernel is **allclose** to the XLA oracle — not bitwise —
and the engine-level guarantee shifts accordingly: greedy streams are
asserted TOKEN-IDENTICAL to the XLA oracle end-to-end across the full
layout matrix (paged x plain/chunked/spec x depth 1+2 x int8 KV x
adapter lanes; tests/test_ragged_attn.py), while seeded streams are
asserted deterministic (same seed => same stream) and are bitwise
arm-identical only under ``variant="gather"``.  Both variants share
the masking rule, the int8 per-block scale operands, and the callers'
LoRA bank plumbing; tier-1 runs both under ``interpret=True`` on CPU,
and the compiled Mosaic lowering is the TPU tier of the ``pallas``
marker.

K/V WRITES stay outside the kernel (the callers' width-masked scatter
— see ``GPTAttention.ragged_window_paged``): lanes past ``width[b]``
land in the slot's own dp shard's SCRATCH block (physical row 0 on an
unsharded engine), which is how the scratch-block and spec-margin
invariants documented in serving/kvcache.py move from per-path code
into one masking rule.

SHARDED LOWERING (``sharded_ragged_paged_attention``): GSPMD cannot
partition a Mosaic-path ``pallas_call`` (the non-interpret TPU
lowering is opaque to the SPMD partitioner), so a 2-D ``(mp, dp)``
serving mesh runs the kernel under ``shard_map``: each mesh shard
executes its OWN grid over its ``B/dp`` slots, with the head axis
pre-sliced per 'mp' shard and each dp shard holding its contiguous
range of pool rows.  Per-slot ``(pos, width, block_table)`` stay
DATA — tables carry global block ids and the wrapper localizes them
by subtracting the shard's row offset (``axis_index('dp') *
blocks_per_shard``), which is exact because the engine's admission
gate only ever hands a slot blocks from its own shard's range.
Under interpret mode on the forced CPU mesh this partitions
identically to what a real Mosaic TPU run would lower, and it is
asserted token-identical to the GSPMD-partitioned XLA oracle across
the serving layout matrix (tests/test_sharded_serving.py).
"""
from __future__ import annotations

import math

VARIANTS = ("stream", "gather")


def _auto_interpret():
    """Pallas interpret mode unless we are actually on TPU — tier-1
    (JAX_PLATFORMS=cpu) exercises the real kernel logic token-for-token
    against the XLA oracle; compiled Mosaic lowering is the TPU tier."""
    import jax
    return jax.default_backend() != "tpu"


def kernel_working_set_bytes(*, variant, block_size, blocks_per_slot,
                             width, num_heads, head_dim):
    """Analytic per-slot VMEM working-set proxy of one kernel instance
    (f32 compute bytes of the live K/V tiles + score tile + carry; the
    serving_longctx bench records it against context length).  The
    streaming variant is FLAT in ``blocks_per_slot`` — its K/V tile is
    one block and its carry is the [W, H, hd] accumulator — while the
    gather variant's whole logical row and [H, W, L] score matrix grow
    linearly with the context ceiling."""
    if variant not in VARIANTS:
        raise ValueError(f"variant must be one of {VARIANTS}, "
                         f"got {variant!r}")
    bs, nb, W = int(block_size), int(blocks_per_slot), int(width)
    H, hd = int(num_heads), int(head_dim)
    q = W * H * hd * 4
    if variant == "gather":
        kv = 2 * nb * bs * H * hd * 4      # the full gathered row, x2
        scores = H * W * nb * bs * 4       # [H, W, L] score/prob tile
        return q + kv + scores + W * H * hd * 4
    kv = 2 * bs * H * hd * 4               # ONE K block + ONE V block
    scores = H * W * bs * 4                # [H, W, block_size] tile
    carry = 2 * H * W * 4 + W * H * hd * 4  # m, l + accumulator
    return q + kv + scores + carry


def _stream_impl(q, k_flat, v_flat, block_tables, pos, width,
                 block_size, interpret, k_scale=None, v_scale=None):
    """Flash-style online-softmax streaming kernel (module docstring):
    fori over the slot's live blocks with running (m, l, acc)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B, W, H, hd = q.shape
    nb = block_tables.shape[1]
    bs = block_size
    scale = 1.0 / math.sqrt(hd)
    quant = k_scale is not None

    def kernel(tables_ref, pos_ref, width_ref, q_ref, k_ref, v_ref,
               *rest):
        if quant:
            ks_ref, vs_ref, o_ref = rest
        else:
            (o_ref,) = rest
        b = pl.program_id(0)
        p = pos_ref[b]
        w = width_ref[b]
        qa = q_ref[0].astype(jnp.float32)                # [W, H, hd]
        s_ids = jax.lax.broadcasted_iota(jnp.int32, (W, bs), 0)
        r_ids = jax.lax.broadcasted_iota(jnp.int32, (W, bs), 1)

        def block(j, scale_ref, pool_ref):
            # gather ONE paged block: physical block ids are runtime
            # data; bs is the only static extent.  Quantized pools
            # dequantize PER STREAMED BLOCK — int8 codes times that
            # block's per-head scale row, right where the block enters
            # the recurrence, never the whole pool.
            idx = tables_ref[b, j]
            blk = pool_ref[pl.ds(idx * bs, bs)]          # [bs, H, hd]
            if scale_ref is not None:
                s = scale_ref[pl.ds(idx, 1)][0]          # [H]
                return blk.astype(jnp.float32) * s[None, :, None]
            return blk.astype(jnp.float32)

        def body(j, carry):
            m, l, acc = carry
            kb = block(j, ks_ref if quant else None, k_ref)
            vb = block(j, vs_ref if quant else None, v_ref)
            sc = jnp.einsum("qhd,khd->hqk", qa, kb) * scale
            # query lane s sees cache positions <= pos + s — the
            # slot's LENGTH does the masking, not a padded shape
            visible = (j * bs + r_ids) <= (p + s_ids)    # [W, bs]
            sc = jnp.where(visible[None, :, :], sc, -1e30)
            bm = jnp.max(sc, axis=2)                     # [H, W]
            new_m = jnp.maximum(m, bm)
            # multiply by the mask, not just the -1e30 floor: a fully
            # masked tile must contribute EXACTLY zero mass even while
            # the running max is still at its -1e30 init (where
            # exp(sc - new_m) would read exp(0) = 1)
            pj = jnp.exp(sc - new_m[:, :, None]) \
                * visible[None, :, :].astype(jnp.float32)
            corr = jnp.exp(m - new_m)                    # [H, W]
            l = l * corr + jnp.sum(pj, axis=2)
            acc = acc * corr[:, :, None] \
                + jnp.einsum("hqk,khd->hqd", pj, vb)
            return new_m, l, acc

        # causal horizon: the last visible position is pos + width - 1
        # (width >= 1; a parked width-0 slot still walks block 0 so
        # the normalizer never hits zero — its lanes are zeroed below
        # anyway).  Blocks past the horizon are fully masked, so
        # skipping them is EXACT — and it is what makes per-tick block
        # walks O(live context), not O(table length).
        n_live = jnp.minimum(
            nb, (p + jnp.maximum(w, 1) - 1) // bs + 1)
        m0 = jnp.full((H, W), -1e30, jnp.float32)
        l0 = jnp.zeros((H, W), jnp.float32)
        a0 = jnp.zeros((H, W, hd), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, n_live, body, (m0, l0, a0))
        ctx = jnp.transpose(acc / l[:, :, None], (1, 0, 2))
        # width as data: lanes past this slot's real window are zeroed
        # (parked slots — width 0 — return all-zero, never-read lanes)
        lane = jax.lax.broadcasted_iota(jnp.int32, (W, 1, 1), 0)
        ctx = jnp.where(lane < w, ctx, 0.0)
        o_ref[0] = ctx.astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec(block_tables.shape, lambda b: (0, 0)),
        pl.BlockSpec(pos.shape, lambda b: (0,)),
        pl.BlockSpec(width.shape, lambda b: (0,)),
        pl.BlockSpec((1, W, H, hd), lambda b: (b, 0, 0, 0)),
        pl.BlockSpec(k_flat.shape, lambda b: (0, 0, 0)),
        pl.BlockSpec(v_flat.shape, lambda b: (0, 0, 0)),
    ]
    operands = [block_tables, pos, width, q, k_flat, v_flat]
    if quant:
        in_specs += [
            pl.BlockSpec(k_scale.shape, lambda b: (0, 0)),
            pl.BlockSpec(v_scale.shape, lambda b: (0, 0)),
        ]
        operands += [k_scale, v_scale]
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, W, H, hd), lambda b: (b, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, W, H, hd), q.dtype),
        interpret=interpret,
    )(*operands)


def _gather_impl(q, k_flat, v_flat, block_tables, pos, width,
                 block_size, interpret, k_scale=None, v_scale=None):
    """Gather-then-softmax kernel (``attn_impl="ragged_gather"``):
    materialize the full logical row, one monolithic softmax —
    bitwise-equal to the XLA oracle, O(context_len) working set."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B, W, H, hd = q.shape
    nb = block_tables.shape[1]
    bs = block_size
    L = nb * bs
    scale = 1.0 / math.sqrt(hd)
    quant = k_scale is not None

    def kernel(tables_ref, pos_ref, width_ref, q_ref, k_ref, v_ref,
               *rest):
        if quant:
            ks_ref, vs_ref, o_ref = rest
        else:
            (o_ref,) = rest
        b = pl.program_id(0)
        p = pos_ref[b]
        w = width_ref[b]

        def rows(pool_ref, scale_ref):
            # kv-block loop: gather this slot's logical [L] row
            # through its block table (physical block ids are runtime
            # data; nb/bs are the only static shapes — note the
            # UNROLLED Python loop: program size and trace time grow
            # with nb, the gather variant's context-ceiling tax).
            # Quantized pools dequantize PER GATHERED BLOCK.
            parts = []
            for j in range(nb):
                blk = pool_ref[pl.ds(tables_ref[b, j] * bs, bs)]
                if scale_ref is not None:
                    s = scale_ref[pl.ds(tables_ref[b, j], 1)][0]  # [H]
                    parts.append(blk.astype(jnp.float32)
                                 * s[None, :, None])
                else:
                    parts.append(blk)
            return jnp.concatenate(parts, axis=0)            # [L, H, hd]

        k_rows = rows(k_ref, ks_ref if quant else None)
        v_rows = rows(v_ref, vs_ref if quant else None)
        qa = q_ref[0].astype(jnp.float32)                    # [W, H, hd]
        # same contraction / mask / softmax as the XLA oracle
        # (_slot_attn), per slot: scores [H, W, L] in f32
        scores = jnp.einsum(
            "qhd,khd->hqk", qa,
            k_rows.astype(jnp.float32)) * scale
        l_ids = jax.lax.broadcasted_iota(jnp.int32, (W, L), 1)
        s_ids = jax.lax.broadcasted_iota(jnp.int32, (W, L), 0)
        # query lane s sees cache positions <= pos + s — the slot's
        # LENGTH does the masking, not a padded shape
        visible = l_ids <= p + s_ids                         # [W, L]
        scores = jnp.where(visible[None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum("hqk,khd->qhd", probs,
                         v_rows.astype(jnp.float32))
        # width as data: lanes past this slot's real window are zeroed
        # (parked slots — width 0 — return all-zero, never-read lanes)
        lane = jax.lax.broadcasted_iota(jnp.int32, (W, 1, 1), 0)
        ctx = jnp.where(lane < w, ctx, 0.0)
        o_ref[0] = ctx.astype(o_ref.dtype)

    in_specs = [
        pl.BlockSpec(block_tables.shape, lambda b: (0, 0)),
        pl.BlockSpec(pos.shape, lambda b: (0,)),
        pl.BlockSpec(width.shape, lambda b: (0,)),
        pl.BlockSpec((1, W, H, hd), lambda b: (b, 0, 0, 0)),
        pl.BlockSpec(k_flat.shape, lambda b: (0, 0, 0)),
        pl.BlockSpec(v_flat.shape, lambda b: (0, 0, 0)),
    ]
    operands = [block_tables, pos, width, q, k_flat, v_flat]
    if quant:
        in_specs += [
            pl.BlockSpec(k_scale.shape, lambda b: (0, 0)),
            pl.BlockSpec(v_scale.shape, lambda b: (0, 0)),
        ]
        operands += [k_scale, v_scale]
    return pl.pallas_call(
        kernel,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, W, H, hd), lambda b: (b, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, W, H, hd), q.dtype),
        interpret=interpret,
    )(*operands)


def ragged_paged_attention(q, k_flat, v_flat, block_tables, pos, width,
                           *, block_size, interpret=None,
                           k_scale=None, v_scale=None,
                           variant="stream"):
    """Ragged paged attention over a slot pool (see module docstring).

    q : [B, W, H, hd] query window per slot (W = the engine's static
        maximum window; real lanes per slot are ``width[b]``).
    k_flat / v_flat : [num_blocks * block_size, H, hd] — the paged
        pools flattened to physical rows (writes already scattered).
        With ``k_scale``/``v_scale`` these are int8 CODE rows.
    block_tables : int32 [B, L // block_size] physical block per
        logical block (row 0 = the scratch block for parked slots).
    pos : int32 [B] window start per slot (tokens already cached).
    width : int32 [B] real query lanes this tick (0 = parked; output
        lanes >= width are zeroed).
    k_scale / v_scale : optional f32 [num_blocks, H] per-block
        per-head dequant multipliers (``Engine(kv_dtype="int8")``):
        the kernel dequantizes each block in-loop — codes times the
        block's scale row, adjacent to the contraction — so the
        logical K/V row never materializes outside VMEM and the whole
        pool is never dequantized.  Pass both or neither.
    variant : ``"stream"`` (default) — flash-style online softmax,
        O(block_size x W) working set, allclose to the oracle;
        ``"gather"`` — materialize-the-row form, O(context_len)
        working set, bitwise-equal to the oracle (the A/B reference
        behind ``attn_impl="ragged_gather"``).
    Returns ctx [B, W, H, hd] in q's dtype.
    """
    import jax.numpy as jnp

    if variant not in VARIANTS:
        raise ValueError(
            f"ragged_paged_attention: variant must be one of "
            f"{VARIANTS}, got {variant!r}")
    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            "ragged_paged_attention: pass both k_scale and v_scale "
            "(quantized pools) or neither (fp pools)")
    if interpret is None:
        interpret = _auto_interpret()
    if k_scale is not None:
        k_scale = jnp.asarray(k_scale, jnp.float32)
        v_scale = jnp.asarray(v_scale, jnp.float32)
    impl = _stream_impl if variant == "stream" else _gather_impl
    return impl(
        q, k_flat, v_flat,
        jnp.asarray(block_tables, jnp.int32),
        jnp.asarray(pos, jnp.int32), jnp.asarray(width, jnp.int32),
        block_size=int(block_size), interpret=bool(interpret),
        k_scale=k_scale, v_scale=v_scale)


def sharded_ragged_paged_attention(q, k_flat, v_flat, block_tables,
                                   pos, width, *, block_size,
                                   mesh=None, interpret=None,
                                   k_scale=None, v_scale=None,
                                   variant="stream"):
    """``shard_map``-partitioned ragged paged attention over a 2-D
    ``(mp, dp)`` serving mesh (module docstring, SHARDED LOWERING).

    Same contract as ``ragged_paged_attention`` plus ``mesh`` (a jax
    Mesh with 'mp'/'dp' axes; defaults to the process-global serving
    mesh, ``distributed.mesh.get_mesh()``).  Each mesh shard runs its
    own kernel grid over the ``B/dp`` slots it owns:

    * q [B, W, H, hd] shards ``P('dp', None, 'mp', None)`` — slot rows
      over 'dp', whole heads pre-sliced over 'mp';
    * k_flat/v_flat [NB*bs, H, hd] shard ``P('dp', 'mp', None)`` —
      each dp shard's contiguous pool-row range, its heads' slice;
    * block_tables [B, L//bs] shard ``P('dp', None)`` and carry GLOBAL
      block ids — the body localizes them by subtracting
      ``axis_index('dp') * blocks_per_shard`` (exact: the engine's
      admission gate allocates a slot's blocks only from its own
      shard's range, serving/kvcache.py BlockPool(shards=...));
    * pos/width [B] shard ``P('dp')``; scales [NB, H] shard
      ``P('dp', 'mp')``.

    The per-shard body is the UNchanged kernel — the partitioning
    this wrapper hand-writes is exactly what interpret mode's HLO
    lowering lets GSPMD derive, which is what the dp parity tests
    pin; on TPU it is the only way to run the Mosaic kernel on a
    mesh at all.  Output shards like q.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    try:
        from jax.experimental.shard_map import shard_map
    except ImportError:  # newer jax: promoted out of experimental
        from jax import shard_map
    if mesh is None:
        from ..distributed import mesh as mesh_mod
        mesh = mesh_mod.get_mesh()
    if mesh is None:
        raise ValueError(
            "sharded_ragged_paged_attention needs a mesh: pass mesh=..."
            " or set the process-global serving mesh "
            "(distributed.mesh.set_mesh / Engine(mesh=...))")
    dp = int(mesh.shape.get("dp", 1))
    mp = int(mesh.shape.get("mp", 1))
    B, W, H, hd = q.shape
    rows = k_flat.shape[0]
    bs = int(block_size)
    if B % dp or (rows // bs) % dp:
        raise ValueError(
            f"sharded ragged kernel: B={B} slots and "
            f"{rows // bs} pool blocks must both divide by the mesh's "
            f"dp degree ({dp})")
    if H % mp:
        raise ValueError(
            f"sharded ragged kernel: H={H} heads must divide by the "
            f"mesh's mp degree ({mp}) — attention shards whole heads")
    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            "sharded_ragged_paged_attention: pass both k_scale and "
            "v_scale (quantized pools) or neither (fp pools)")
    quant = k_scale is not None
    if interpret is None:
        interpret = _auto_interpret()
    interpret = bool(interpret)

    def body(q_l, k_l, v_l, tables_l, pos_l, width_l, *scales):
        # tables hold GLOBAL block ids; this shard's pool slice starts
        # at row offset axis_index('dp') * blocks_per_shard
        nb_local = k_l.shape[0] // bs
        local = tables_l - jax.lax.axis_index("dp") * nb_local
        ks, vs = scales if scales else (None, None)
        return ragged_paged_attention(
            q_l, k_l, v_l, local, pos_l, width_l, block_size=bs,
            interpret=interpret, k_scale=ks, v_scale=vs,
            variant=variant)

    qspec = P("dp", None, "mp", None)
    kvspec = P("dp", "mp", None)
    in_specs = [qspec, kvspec, kvspec, P("dp", None), P("dp"),
                P("dp")]
    args = [q, k_flat, v_flat,
            jnp.asarray(block_tables, jnp.int32),
            jnp.asarray(pos, jnp.int32),
            jnp.asarray(width, jnp.int32)]
    if quant:
        in_specs += [P("dp", "mp"), P("dp", "mp")]
        args += [jnp.asarray(k_scale, jnp.float32),
                 jnp.asarray(v_scale, jnp.float32)]
    fn = shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=qspec, check_rep=False)
    return fn(*args)
