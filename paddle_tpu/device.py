"""paddle.device (reference: python/paddle/device.py).

Device management over jax devices; "gpu:0"-style strings map to the TPU
chips XLA exposes.
"""
from __future__ import annotations

from .core.device import (set_device, get_device,  # noqa: F401
                          is_compiled_with_cuda, is_compiled_with_xpu,
                          is_compiled_with_tpu)


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return []


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def XPUPlace(dev_id=0):  # noqa: N802 — reference place-factory casing
    from .core import device as d
    return d.current_place()


def cuda_device_count():
    import jax
    return len([d for d in jax.devices() if d.platform != "cpu"])
