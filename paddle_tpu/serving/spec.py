"""Speculative decoding proposers: draft cheap, verify in one pass.

The engine's decode tick emits exactly one token per slot per
dispatch, so tokens/sec is dispatch-bound long before the hardware is.
Speculative decoding breaks the one-dispatch-one-token coupling: a
PROPOSER guesses ``k`` draft tokens per slot from information the
engine already has, and ONE windowed target-model dispatch
(``GPTModel._compiled_spec_verify_fn``) scores all k+1 positions —
the engine then accepts the longest prefix where the target's argmax
equals the draft, plus the one "bonus" token the target produced at
the first mismatch.  Greedy acceptance is LOSSLESS: every emitted
token is the target model's own pick given its true prefix, so
drafts only decide how many tokens each dispatch yields (1..k+1) and
speculative greedy outputs are token-identical to the non-speculative
engine (tests/test_serving.py asserts it).  Wrong drafts cost nothing
beyond the fixed window compute — the engine's write cursor simply
does not advance over rejected lanes.

Under the engine's default ``sample_mode="device"`` the verify
dispatch ALSO picks each lane's token and counts the accepted prefix
on device (``GPTModel._compiled_fused_spec_verify_fn``), so a verify
tick downloads picks ``[B, W]`` + accept counts ``[B]`` instead of
the full ``[B, W, V]`` logits; ``sample_mode="host"`` keeps the
legacy logits pull + host accept loop.  Proposers are mode-agnostic —
they only ever see the host-side token history.

Two proposers ship here:

* ``PromptLookupProposer`` — n-gram match against the slot's own
  prompt + emitted history (prompt-lookup decoding): zero extra
  model, pure numpy on the host, ideal for the summarization / code /
  chat regime where output n-grams repeat.  This is the production
  CPU-side default.
* ``DraftModelProposer`` — a smaller GPT drafts autoregressively.
  The draft model must share the target's tokenizer/vocabulary (the
  engine cross-checks ``vocab_size`` at construction).  Reference
  implementation: it re-runs the history through ``generate()`` per
  proposal, which is simple and correct but O(history) per tick —
  production drafting would keep per-slot draft K/V hot.

A proposer is a plain strategy object — stateless across requests —
so one instance can serve every slot of an engine.

Robustness contract: a proposer that RAISES mid-draft degrades, it
does not kill the tick — the engine catches the exception, counts
``serving.proposer_failures``, and runs the verify window with zero
drafts (plain one-token decode speed) so no in-flight request is
evicted over a drafting hiccup.  The deterministic chaos harness
(serving/faults.py, ``spec_draft`` site) exercises exactly this path.
"""
from __future__ import annotations

import numpy as np


class Proposer:
    """Draft-token source for speculative decoding.

    ``propose(history, k)`` receives one slot's full token history
    (prompt + everything emitted so far, the last entry being the
    token whose K/V the next dispatch will write) and returns up to
    ``k`` int draft tokens predicted to FOLLOW it.  Returning fewer
    than ``k`` (or none) is always safe: the engine pads the window by
    repeating the current token, but pad lanes are pure FILLER for the
    static window shape — they are never counted as proposed lanes,
    can never be accepted, and their garbage K/V is rewritten before
    any query can see it, so a shortfall costs nothing and corrupts no
    metric.

    ``vocab_size`` (optional): when not None, the engine asserts it
    matches the target model's vocabulary at construction — a draft
    from a different tokenizer would never match and only burn the
    window compute.
    """

    vocab_size = None

    def propose(self, history, k):
        raise NotImplementedError


class PromptLookupProposer(Proposer):
    """Prompt-lookup drafting: find the most recent earlier occurrence
    of the history's trailing ``ngram`` tokens and propose the tokens
    that followed it.  The host-side twin of
    ``generate(compiled='speculative')``'s on-device draft_row —
    free of any draft model, which keeps the whole speculative
    subsystem runnable on the CPU tier-1 suite."""

    def __init__(self, ngram=3, max_window=1024):
        ngram = int(ngram)
        if ngram < 1:
            raise ValueError(f"ngram must be >= 1, got {ngram}")
        max_window = int(max_window)
        if max_window < ngram + 1:
            raise ValueError(
                f"max_window ({max_window}) must exceed ngram "
                f"({ngram}) or no match could ever land")
        self.ngram = ngram
        # bound the host-side scan: propose() runs per slot per
        # decode tick, and hits are overwhelmingly recent — a fixed
        # lookback keeps the drafting cost O(max_window), independent
        # of how long the sequence grows
        self.max_window = max_window

    def propose(self, history, k):
        h = np.asarray(history, np.int64).reshape(-1)[-self.max_window:]
        n = self.ngram
        if len(h) < n + 1:
            return h[:0]
        pat = h[-n:]
        # candidate windows must end strictly before the history's
        # last position (the trailing pattern itself never matches)
        wins = np.lib.stride_tricks.sliding_window_view(h[:-1], n)
        hits = np.nonzero((wins == pat[None, :]).all(axis=1))[0]
        if len(hits) == 0:
            return h[:0]
        j = int(hits[-1])          # most recent occurrence wins
        return h[j + n:j + n + k]


class DraftModelProposer(Proposer):
    """Draft with a smaller GPT sharing the target's tokenizer/vocab:
    greedy-decode ``k`` continuation tokens of the slot's history.

    The draft runs EAGER (uncompiled) on purpose: history length grows
    every tick, and a compiled prefill per distinct length would
    thrash the program cache; eager drafting is correct at any length
    with zero compiles.  Histories longer than the draft model's
    position table are tail-truncated — a draft from a clipped context
    is still just a guess, and verification keeps it honest.

    ``weight_dtype="int8"`` relayouts the draft's transformer blocks
    through weight-only int8 (serving/quant.py) before first use —
    drafts are pure guesses that verification keeps honest, so the
    draft model is the SAFEST place to quantize aggressively: a
    rounding-flipped draft token costs at most one accepted lane,
    never output correctness."""

    def __init__(self, draft_model, weight_dtype=None):
        if weight_dtype not in (None, "int8"):
            raise ValueError(
                f"DraftModelProposer: unsupported weight_dtype "
                f"{weight_dtype!r} (only 'int8')")
        if getattr(draft_model, "scan_layers", False):
            draft_model = draft_model._sync_decode_twin()
        draft_model.eval()
        if weight_dtype == "int8":
            from .quant import relayout_weights_int8
            relayout_weights_int8(draft_model)
        self.model = draft_model
        self.vocab_size = int(
            draft_model.embeddings.word_embeddings.weight.shape[0])
        self._max_position = int(
            draft_model.embeddings.position_embeddings.weight.shape[0])

    def propose(self, history, k):
        h = np.asarray(history, np.int32).reshape(-1)
        keep = self._max_position - int(k)
        if keep < 1:
            return h[:0]
        if len(h) > keep:
            h = h[-keep:]
        out = self.model.generate(h[None, :], max_new_tokens=int(k))
        return np.asarray(out.numpy()[0][len(h):], np.int32)
