"""HTTP front door for the multi-replica Router (serving.router).

The router-tier twin of ``serving.httpd``: handler threads block on
``Router.generate`` (which retries / hedges / fails over across the
replica fleet) the same way engine handlers block on
``Request.result()``.

  POST /generate    same body as the engine endpoint; the response
                    additionally carries ``replica`` (who served it)
                    and ``attempts``.  ``model`` (or ``adapter``)
                    routes to replicas advertising that LoRA adapter
                    — 404 ``unknown_adapter`` when none does.
                    ``stream: true`` answers as SSE (token / done /
                    error frames, exactly like httpd's) fed by the
                    router's live ``on_token`` stream — a replica
                    dying mid-response fails over and the resumed
                    tokens continue the SAME stream seamlessly.
                    Buffered errors are JSON with a machine-readable
                    ``reason``: 503 ``no_replicas`` / 502
                    ``request_failed`` (the classified replica cause
                    is included), 400 ``bad_request``.
  POST /rebalance   operator preempt-and-migrate: body
                    ``{"source": NAME, "request_id"?, "min_tokens"?}``
                    exports one live stream off the named replica;
                    the router re-lands it on a peer (in-process
                    replica fleets — see ``Router.rebalance``).
  GET  /healthz     router liveness + the replica table summary
                    (counts by health state, breaker states)
  GET  /livez       200 while the process serves
  GET  /readyz      200 when at least one replica is routable,
                    503 ``no_replicas`` otherwise
  GET  /replicas    full registry view: per-replica state, breaker,
                    probed load signals, supervisor incarnation,
                    address — the surface tools/timeline.py uses to
                    pull every replica's /debug/trace next to the
                    router's own
  GET  /metrics     Prometheus exposition of the router's registry
  GET  /debug/trace the router's span ring (route.pick/route.retry/
                    route.hedge/probe) as chrome-trace JSON

``main()`` runs a standalone routerd over a static replica list:

  python -m paddle_tpu.serving.routerd \
      --replica http://host1:8000 --replica http://host2:8000

(each ``--replica`` may be ``name=url`` or a bare url).  For a
spawned local fleet — N engine processes on one host — use
``distributed/launch.py`` to start the engines and pass their ports
here, or build the fleet in-process with ``InProcessReplica`` (see
``examples/serving_router.py``).
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
from http.server import ThreadingHTTPServer

from .. import monitor
from .httpd import JsonHandler
from .router import (HttpReplicaClient, NoReplicasAvailable,
                     RequestFailed, Router, RouterPolicy,
                     UnknownModel)
from .stream import sse_format

# states a /readyz considers routable
_ROUTABLE = ("healthy", "degraded")


class _Handler(JsonHandler):
    # the JSON-with-reason plumbing (incl. the send_error override)
    # is shared with the engine's httpd handler via JsonHandler
    router = None   # bound per-server by the factory below

    def _replica_summary(self):
        rows = self.router.replicas()
        by_state = {}
        for r in rows:
            by_state[r["state"]] = by_state.get(r["state"], 0) + 1
        return rows, by_state

    def _rebalance(self, body):
        source = body.get("source")
        if not source:
            raise ValueError("source (a replica name) is required")
        return self.router.rebalance(
            source, request_id=body.get("request_id"),
            min_tokens=int(body.get("min_tokens", 1)),
            timeout=float(body.get("timeout", 10.0)))

    def do_GET(self):
        rt = self.router
        if self.path == "/metrics":
            self._send(200, monitor.render_prometheus(rt.registry),
                       ctype="text/plain; version=0.0.4; "
                             "charset=utf-8")
        elif self.path == "/healthz":
            rows, by_state = self._replica_summary()
            by_role = {}
            for r in rows:
                by_role[r["role"]] = by_role.get(r["role"], 0) + 1
            self._send_json(200, {
                "status": "ok", "live": True,
                "ready": any(r["state"] in _ROUTABLE for r in rows),
                "replicas_total": len(rows),
                "replicas_by_state": by_state,
                "replicas_by_role": by_role,
                "breakers_open": sum(
                    1 for r in rows if r["breaker"] != "closed"),
            })
        elif self.path == "/livez":
            self._send_json(200, {"status": "ok", "live": True})
        elif self.path == "/readyz":
            rows, by_state = self._replica_summary()
            if any(r["state"] in _ROUTABLE for r in rows):
                self._send_json(200, {"status": "ok", "ready": True,
                                      "replicas_by_state": by_state})
            else:
                self._send_json(503, {
                    "status": "unavailable", "ready": False,
                    "reason": "no_replicas",
                    "replicas_by_state": by_state})
        elif self.path == "/replicas":
            self._send_json(200, {"replicas": self.router.replicas()})
        elif self.path == "/debug/trace":
            self._send(200, json.dumps(rt.chrome_trace()),
                       headers={"Content-Disposition":
                                'attachment; filename="router-trace'
                                '.json"'})
        else:
            self._send_json(404, {"error": f"no route {self.path}",
                                  "reason": "not_found"})

    def do_POST(self):
        if self.path == "/rebalance":
            try:
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                out = self._rebalance(body)
            except KeyError as e:
                self._send_json(404, {"error": str(e),
                                      "reason": "not_found"})
            except (TypeError, ValueError,
                    json.JSONDecodeError) as e:
                self._send_json(400, {"error": f"bad request: {e}",
                                      "reason": "bad_request"})
            except Exception as e:
                self._send_json(503, {"error": str(e),
                                      "reason": "migrate_declined"})
            else:
                self._send_json(200, {
                    "completed": bool(out.get("completed")),
                    "generated": len(out.get("generated") or [])})
            return
        if self.path != "/generate":
            self._send_json(404, {"error": f"no route {self.path}",
                                  "reason": "not_found"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            prompt = body["prompt"]
            if not isinstance(prompt, (list, tuple)) or not prompt:
                raise ValueError(
                    "prompt must be a non-empty list of token ids")
            kwargs = dict(
                max_new_tokens=int(body.get("max_new_tokens", 16)),
                eos_token_id=body.get("eos_token_id"),
                temperature=float(body.get("temperature", 1.0)),
                top_k=int(body.get("top_k", 0)),
                top_p=float(body.get("top_p", 1.0)),
                seed=body.get("seed"),
                priority=int(body.get("priority", 0)),
                tenant=body.get("tenant"),
                timeout=body.get("timeout"),
                model=body.get("model", body.get("adapter")))
        except (KeyError, TypeError, ValueError,
                json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}",
                                  "reason": "bad_request"})
            return
        if body.get("stream"):
            self._stream_generate(prompt, kwargs)
            return
        try:
            out = self.router.generate(prompt, **kwargs)
        except NoReplicasAvailable as e:
            self._send_json(503, {"error": str(e),
                                  "reason": "no_replicas"})
            return
        except UnknownModel as e:
            self._send_json(404, {"error": str(e),
                                  "reason": "unknown_adapter"})
            return
        except RequestFailed as e:
            cause = e.cause
            self._send_json(502, {
                "error": str(e), "reason": "request_failed",
                "cause": (type(cause).__name__ if cause is not None
                          else None)})
            return
        except (TypeError, ValueError) as e:
            self._send_json(400, {"error": str(e),
                                  "reason": "bad_request"})
            return
        self._send_json(200, out)

    def _stream_generate(self, prompt, kwargs):
        """SSE out over the router's live token stream.  The router
        call runs on a worker thread feeding a queue; this handler
        thread writes frames as they land (``:hb`` comments when
        quiet).  The FIRST queue item decides the response shape: a
        fast failure (unknown adapter, empty fleet) still gets its
        proper HTTP status, because no SSE header has been committed
        yet.  A failover mid-stream is invisible here — the router
        splices the resumed tokens into the same ``on_token`` feed,
        so the client sees one uninterrupted stream."""
        import queue as _queue
        q = _queue.Queue()
        res = {}

        def run():
            try:
                res["out"] = self.router.generate(
                    prompt, on_token=lambda t: q.put(("tok", t)),
                    **kwargs)
            except Exception as e:
                res["err"] = e
            q.put(("end", None))

        threading.Thread(target=run, daemon=True,
                         name="paddle_tpu-routerd-stream").start()
        kind, val = q.get()
        if kind == "end" and "err" in res:
            e = res["err"]
            if isinstance(e, UnknownModel):
                self._send_json(404, {"error": str(e),
                                      "reason": "unknown_adapter"})
            elif isinstance(e, NoReplicasAvailable):
                self._send_json(503, {"error": str(e),
                                      "reason": "no_replicas"})
            elif isinstance(e, RequestFailed):
                cause = e.cause
                self._send_json(502, {
                    "error": str(e), "reason": "request_failed",
                    "cause": (type(cause).__name__
                              if cause is not None else None)})
            else:
                self._send_json(500, {"error": str(e),
                                      "reason": "internal"})
            return
        self.close_connection = True
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("X-Accel-Buffering", "no")
        self.send_header("Connection", "close")
        self.end_headers()
        sent = 0
        try:
            while kind != "end":
                if kind == "tok":
                    self.wfile.write(sse_format(
                        {"token": int(val), "index": sent},
                        event="token"))
                    sent += 1
                else:
                    self.wfile.write(sse_format(comment="hb"))
                self.wfile.flush()
                try:
                    kind, val = q.get(timeout=0.25)
                except _queue.Empty:
                    kind, val = "hb", None
            err = res.get("err")
            if err is None and "out" in res:
                out = dict(res["out"])
                out["streamed"] = sent
                self.wfile.write(sse_format(out, event="done"))
            else:
                self.wfile.write(sse_format(
                    {"error": str(err),
                     "reason": ("no_replicas"
                                if isinstance(err, NoReplicasAvailable)
                                else "request_failed"
                                if isinstance(err, RequestFailed)
                                else "internal"),
                     "retry_after": None}, event="error"))
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the client vanished mid-stream: the fleet still lands
            # the request; this socket just stops listening
            pass


class RouterServer:
    """Router prober + ThreadingHTTPServer, each on its own daemon
    thread.  ``with RouterServer(router) as srv: ... srv.address``."""

    def __init__(self, router, host="127.0.0.1", port=0):
        self.router = router
        handler = type("BoundRouterHandler", (_Handler,),
                       {"router": router})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self.httpd.server_address[:2]
        self._http_thread = None

    @property
    def address(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        self.router.start()   # background prober
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="paddle_tpu-routerd-http")
        self._http_thread.start()
        return self

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
            self._http_thread = None
        self.router.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False


def parse_replica_spec(spec):
    """``NAME=URL`` or a bare URL (name defaults to host:port...).
    Only the text BEFORE the first ``=`` with no ``://`` in it is a
    name — a bare URL whose query string contains ``=`` must not be
    split."""
    name, sep, url = spec.partition("=")
    if not sep or "://" in name:
        return spec.split("//")[-1], spec
    return name, url


def main(argv=None):
    p = argparse.ArgumentParser(
        description="HTTP front door routing over N engine replicas "
                    "(health-probed, prefix-affinity, retry/hedge/"
                    "circuit-break)")
    p.add_argument("--replica", action="append", default=[],
                   metavar="[NAME=]URL", required=False,
                   help="replica endpoint (repeatable); NAME defaults "
                        "to the URL's host:port")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--probe-interval", type=float, default=1.0)
    p.add_argument("--no-affinity", action="store_true",
                   help="route by load only (A/B the affinity gain)")
    p.add_argument("--hedge", action="store_true",
                   help="enable tail-latency hedging for idempotent "
                        "requests")
    p.add_argument("--disaggregate", action="store_true",
                   help="prefill/decode disaggregation: prefill on a "
                        "prefill-role replica, migrate the KV blocks, "
                        "decode on a decode-role replica")
    p.add_argument("--prefix-warm", action="store_true",
                   help="on an affinity miss, warm the chosen "
                        "replica's prefix cache from the affinity "
                        "target before dispatching")
    args = p.parse_args(argv)
    if not args.replica:
        p.error("at least one --replica is required")
    policy = RouterPolicy(probe_interval_s=args.probe_interval,
                          affinity=not args.no_affinity,
                          hedge=args.hedge,
                          disaggregate=args.disaggregate,
                          prefix_warm=args.prefix_warm)
    router = Router(policy=policy)
    for spec in args.replica:
        name, url = parse_replica_spec(spec)
        router.add_replica(name, HttpReplicaClient(url))
    # fail fast on typo'd addresses: an entirely unreachable fleet is
    # a configuration error, not a fleet to keep probing
    router.probe_once()
    unreachable = [r.name for r in router._reps()
                   if r.probe_failures > 0]
    if len(unreachable) == len(router._reps()):
        p.error("no replica answered its first probe: "
                + ", ".join(unreachable))
    for name in unreachable:
        print(f"warning: replica {name} unreachable (kept in the "
              "registry; the prober will retry)", file=sys.stderr)
    srv = RouterServer(router, host=args.host, port=args.port).start()
    print(f"routerd on {srv.address} over "
          f"{len(router.replicas())} replica(s)")
    try:
        srv._http_thread.join()
    except KeyboardInterrupt:
        pass
    finally:
        srv.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
