"""Multi-adapter (LoRA) serving: low-rank deltas as per-slot lanes.

The engine's whole design rides on one idea: anything that varies per
request is DATA inside one compiled program, never shape (sampling
params, positions, block tables, int8 codes — and now LoRA deltas).
An adapter contributes ``delta = x @ A^T @ B^T`` on the attention
output projection; stacking every adapter's (zero-padded) factors
into two dense banks

    a_bank : [n_lanes, n_layers, r_max, E]
    b_bank : [n_lanes, n_layers, E, r_max]

turns "which adapter" into a per-slot int32 ``adapter_id`` that
gathers a lane out of the banks *inside* the traced computation.
Lane 0 is all-zeros — the base model — so un-adapted requests share
the very same program at zero extra cost semantics (the einsum against
a zero lane is exactly zero).  The bank shapes are fixed at engine
construction (``max_adapters`` lanes), so hot-loading adapter #2, #3,
... is a pure ``.at[lane].set`` — the compile probe sees NOTHING.

Ranks smaller than ``r_max`` are zero-padded, which is mathematically
exact (padded rows/cols contribute 0 to the product).  The classic
``alpha / rank`` scaling is folded into the stored B factor once at
registration, so the hot path multiplies nothing extra.

The merged-weights oracle (``LoRAAdapter.merged_delta`` /
``merge_into``) is the ground truth the tests pin the traced lanes
against: folding ``scale * (B @ A)^T`` into ``out_proj.weight`` (the
framework's Linear keeps weights ``[in, out]`` with ``y = x W + b``)
must produce token-identical decodes.
"""
from __future__ import annotations

import threading

import numpy as np


class UnknownAdapter(KeyError):
    """Request named an adapter this engine has not loaded (the HTTP
    edge maps this to 404 ``{"reason": "unknown_adapter"}``)."""


class AdapterInUse(RuntimeError):
    """unload_adapter refused: in-flight requests still pin the
    adapter (queued or decoding); retry after they drain."""


class RegistryFull(RuntimeError):
    """No free lane: the engine was built with ``max_adapters`` lanes
    and all of them hold live adapters."""


class LoRAAdapter:
    """One adapter's factors.

    A : [rank, E] or [n_layers, rank, E]  — the down-projection
    B : [E, rank] or [n_layers, E, rank]  — the up-projection
    2-D factors are broadcast to every layer.  ``alpha`` is the usual
    LoRA scaling numerator (effective scale ``alpha / rank``; default
    scale 1.0).  The delta applies to the attention output projection:
    ``y = out_proj(x) + scale * (x @ A^T) @ B^T``.
    """

    def __init__(self, rank, A, B, alpha=None, name=None):
        rank = int(rank)
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        A = np.asarray(A, np.float32)
        B = np.asarray(B, np.float32)
        if A.ndim == 2:
            A = A[None]
        if B.ndim == 2:
            B = B[None]
        if A.ndim != 3 or B.ndim != 3:
            raise ValueError(
                f"A/B must be [rank, E]/[E, rank] (optionally with a "
                f"leading n_layers axis), got {A.shape} / {B.shape}")
        if A.shape[-2] != rank or B.shape[-1] != rank:
            raise ValueError(
                f"factor shapes {A.shape} / {B.shape} disagree with "
                f"rank={rank} (want [..., {rank}, E] / [..., E, {rank}])")
        if A.shape[-1] != B.shape[-2]:
            raise ValueError(
                f"hidden dims disagree: A {A.shape} vs B {B.shape}")
        if A.shape[0] != B.shape[0]:
            raise ValueError(
                f"layer counts disagree: A {A.shape} vs B {B.shape}")
        self.rank = rank
        self.hidden = int(A.shape[-1])
        self.A = A
        self.B = B
        self.alpha = float(alpha) if alpha is not None else float(rank)
        self.scale = self.alpha / rank
        self.name = name

    @classmethod
    def random(cls, rank, hidden, n_layers=1, seed=0, scale=0.02,
               name=None):
        """Gaussian factors for tests/examples/benchmarks — ``scale``
        keeps the delta small enough that adapted decodes stay
        plausible but distinct from the base model."""
        rng = np.random.RandomState(seed)
        A = rng.normal(0.0, scale, (n_layers, rank, hidden))
        B = rng.normal(0.0, scale, (n_layers, hidden, rank))
        return cls(rank, A, B, name=name)

    def factors(self, n_layers, r_max):
        """(a, b) zero-padded to the bank slot shape:
        a [n_layers, r_max, E], b [n_layers, E, r_max] — the LoRA
        scale folded into b so the hot path never multiplies it."""
        if self.rank > r_max:
            raise ValueError(
                f"adapter rank {self.rank} exceeds the engine's "
                f"r_max={r_max} (fixed at construction)")
        A, B = self.A, self.B
        if A.shape[0] == 1 and n_layers > 1:
            A = np.broadcast_to(A, (n_layers,) + A.shape[1:])
            B = np.broadcast_to(B, (n_layers,) + B.shape[1:])
        if A.shape[0] != n_layers:
            raise ValueError(
                f"adapter has {A.shape[0]} layers of factors, model "
                f"has {n_layers}")
        E = self.hidden
        a = np.zeros((n_layers, r_max, E), np.float32)
        b = np.zeros((n_layers, E, r_max), np.float32)
        a[:, :self.rank, :] = A
        b[:, :, :self.rank] = B * self.scale
        return a, b

    def merged_delta(self, n_layers):
        """[n_layers, E, E] weight delta in the framework's Linear
        layout ([in, out], ``y = x W``): ``scale * (B @ A)^T`` per
        layer — the offline merged-weights oracle."""
        A, B = self.A, self.B
        if A.shape[0] == 1 and n_layers > 1:
            A = np.broadcast_to(A, (n_layers,) + A.shape[1:])
            B = np.broadcast_to(B, (n_layers,) + B.shape[1:])
        return np.stack([
            self.scale * (B[i] @ A[i]).T for i in range(n_layers)
        ]).astype(np.float32)

    def merge_into(self, model):
        """Fold this adapter into ``model``'s attention out_proj
        weights in place — the oracle a lane-gathered engine must
        match token-for-token.  Returns the model."""
        blocks = list(model.blocks)
        delta = self.merged_delta(len(blocks))
        for i, blk in enumerate(blocks):
            w = blk.attn.out_proj.weight
            w.set_value(w.numpy() + delta[i].astype(w.numpy().dtype))
        return model


class _Loaded:
    __slots__ = ("adapter", "lane", "pins")

    def __init__(self, adapter, lane):
        self.adapter = adapter
        self.lane = lane
        self.pins = 0


class AdapterRegistry:
    """Name -> lane mapping plus the two device banks.

    Built once per engine; lane 0 is the all-zeros base lane and is
    never assigned.  ``load``/``unload`` mutate the banks with
    ``.at[lane].set`` — bank SHAPES never change, so the engine's
    compiled programs are untouched.  Pin counts (one per in-flight
    request) guard unload; the engine pins at submit and unpins via
    the request's finish callback.

    Thread safety: name/pin bookkeeping takes ``_lock`` (submits land
    from HTTP handler threads); bank mutation is reserved to the
    engine thread between ticks (the load/unload demands drain the
    async ring first), so readers of ``a_bank``/``b_bank`` — the
    dispatch sites — see a stable snapshot per tick.
    """

    def __init__(self, n_layers, hidden, max_adapters, r_max):
        import jax.numpy as jnp
        if max_adapters < 1:
            raise ValueError(
                f"max_adapters must be >= 1, got {max_adapters}")
        if r_max < 1:
            raise ValueError(f"r_max must be >= 1, got {r_max}")
        self.n_layers = int(n_layers)
        self.hidden = int(hidden)
        self.max_adapters = int(max_adapters)
        self.r_max = int(r_max)
        self.n_lanes = self.max_adapters + 1  # +1: the base lane 0
        self.a_bank = jnp.zeros(
            (self.n_lanes, self.n_layers, self.r_max, self.hidden),
            jnp.float32)
        self.b_bank = jnp.zeros(
            (self.n_lanes, self.n_layers, self.hidden, self.r_max),
            jnp.float32)
        self._lock = threading.Lock()
        self._by_name = {}
        self._free = list(range(self.n_lanes - 1, 0, -1))  # pop() -> 1

    # -- inventory -------------------------------------------------------
    def names(self):
        with self._lock:
            return sorted(self._by_name)

    def __contains__(self, name):
        with self._lock:
            return name in self._by_name

    def __len__(self):
        with self._lock:
            return len(self._by_name)

    def lane(self, name):
        """Resolve a request's adapter name to its bank lane."""
        with self._lock:
            entry = self._by_name.get(name)
            if entry is None:
                raise UnknownAdapter(
                    f"unknown adapter {name!r}: loaded="
                    f"{sorted(self._by_name)}")
            return entry.lane

    def pins(self, name):
        with self._lock:
            entry = self._by_name.get(name)
            return 0 if entry is None else entry.pins

    def describe(self):
        """{name: {"lane", "rank", "pins"}} — the /debug surface."""
        with self._lock:
            return {n: {"lane": e.lane, "rank": e.adapter.rank,
                        "pins": e.pins}
                    for n, e in sorted(self._by_name.items())}

    # -- pinning (submit / finish) ---------------------------------------
    def pin(self, name):
        """Take a lane reference for an in-flight request; returns the
        lane.  Pinned adapters refuse unload — a mid-stream bank swap
        would silently change the request's model."""
        with self._lock:
            entry = self._by_name.get(name)
            if entry is None:
                raise UnknownAdapter(
                    f"unknown adapter {name!r}: loaded="
                    f"{sorted(self._by_name)}")
            entry.pins += 1
            return entry.lane

    def unpin(self, name):
        with self._lock:
            entry = self._by_name.get(name)
            if entry is not None and entry.pins > 0:
                entry.pins -= 1

    # -- bank mutation (engine thread, between ticks) --------------------
    def load(self, name, adapter):
        """Write ``adapter`` into a free lane under ``name``; returns
        the lane.  Shapes are validated against the banks — loading is
        pure data movement, never a retrace."""
        if not isinstance(adapter, LoRAAdapter):
            raise TypeError(
                f"expected LoRAAdapter, got {type(adapter).__name__}")
        if adapter.hidden != self.hidden:
            raise ValueError(
                f"adapter hidden={adapter.hidden} vs model "
                f"hidden={self.hidden}")
        a, b = adapter.factors(self.n_layers, self.r_max)
        with self._lock:
            if name in self._by_name:
                raise ValueError(
                    f"adapter {name!r} already loaded (unload first)")
            if not self._free:
                raise RegistryFull(
                    f"all {self.max_adapters} adapter lanes in use: "
                    f"{sorted(self._by_name)}")
            lane = self._free.pop()
            self._by_name[name] = _Loaded(adapter, lane)
        self.a_bank = self.a_bank.at[lane].set(a)
        self.b_bank = self.b_bank.at[lane].set(b)
        return lane

    def unload(self, name):
        """Zero ``name``'s lane and free it.  Refuses (AdapterInUse)
        while any in-flight request pins the adapter."""
        with self._lock:
            entry = self._by_name.get(name)
            if entry is None:
                raise UnknownAdapter(
                    f"unknown adapter {name!r}: loaded="
                    f"{sorted(self._by_name)}")
            if entry.pins > 0:
                raise AdapterInUse(
                    f"adapter {name!r} pinned by {entry.pins} "
                    f"in-flight request(s); drain them before unload")
            del self._by_name[name]
            lane = entry.lane
            self._free.append(lane)
        self.a_bank = self.a_bank.at[lane].set(0.0)
        self.b_bank = self.b_bank.at[lane].set(0.0)
        return lane
