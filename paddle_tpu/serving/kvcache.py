"""Paged KV-cache: block pool, refcounted copy-on-write, prefix reuse.

PR 1's engine reserves one contiguous ``max_seq_len`` K/V row per slot:
HBM is held for the worst case of every request, and identical prompt
prefixes (system prompts, few-shot headers) are recomputed and stored
once PER REQUEST.  This module is the block-granular fix (the Ragged
Paged Attention direction, PAPERS.md 2604.15464): the engine's K/V
pools are carved into fixed-size blocks, a slot's logical cache row is
the gather of its BLOCK TABLE, identical prefixes share physical
blocks (refcounted, copy-on-write), and finished prompts stay resident
in a token-trie ``PrefixCache`` so later requests skip prefill for the
shared span — with LRU eviction returning blocks under pool pressure.

Host-side METADATA only: the engine owns the device arrays (the same
split as Scheduler vs Engine), and block ids here are row indices into
the engine's per-layer ``[num_blocks, block_size, H, hd]`` pools (one
id indexes every layer — the table is layer-invariant).  Everything is
driven from the single engine loop thread, so no locking (``submit``
never touches the cache).

Reference protocol (who holds how many refs on a block):

* ``alloc`` hands blocks out at refcount 1 — the allocating slot's ref.
* ``PrefixCache.insert`` takes ONE extra ref per newly registered
  block (the cache's own); already-cached spans are left alone.
* ``PrefixCache.match`` takes one ref per matched block ON BEHALF OF
  the adopting slot.
* Slot eviction decrefs every block in the slot's table exactly once;
  blocks that were cached drop to the cache's ref and stay resident,
  decode-span blocks drop to 0 and return to the free list.
* ``evict`` drops cache refs (LRU, unreferenced leaves first) until
  enough blocks free up.

Cursor-rewind invariant (speculative decoding, serving/spec.py): a
slot's KV WRITE CURSOR (``Slot.pos``) may move backward relative to
rows already written — the verify window writes k+1 rows but the
engine advances the cursor only over accepted lanes.  The block layer
stays entirely out of that loop BY CONSTRUCTION: the admission gate
reserves the worst case INCLUDING the ``spec_k`` window margin, so
every window position (rejected lanes included) lands in blocks the
slot already owns, rejected rows are plain garbage inside an owned
block that the next window overwrites, and rollback therefore never
allocs, frees, or refcounts a block.  Nothing here tracks a cursor —
which is the invariant: no pool state can go stale on a rewind.

Scratch-block / spec-margin writes under ``attn_impl="ragged"``: the
XLA dispatches enforce the invariants above with three separate
mechanisms (parked slots' all-zero tables route writes to the
reserved scratch block — physical row 0; the spec margin absorbs
rejected verify lanes; chunked prefill's ``true_len`` masks pad
lanes into row 0).  The ragged Pallas path folds all three into ONE
KERNEL-SIDE MASKING RULE: every window lane ``s >= width[slot]``
scatters into physical row 0, where ``width`` is the per-slot REAL
window width carried as kernel data (0 for a parked slot, the chunk
length for a prefill lane, k+1 for a verify window whose rejected
lanes still land inside the reserved margin).  The pool-layer
contract is unchanged — no live request ever reads row 0, and no
write ever touches a block the slot does not own — it is simply
enforced in one place (``GPTAttention.ragged_window_paged`` +
ops/ragged_paged_attn.py) instead of three.  The rule is
READ-SIDE-invariant across kernel bodies: the streaming
online-softmax kernel (``attn_impl="ragged"``) walks a slot's table
only up to the lane's causal horizon ``ceil((pos + width) /
block_size)`` and masks per streamed block, while the gather body
(``attn_impl="ragged_gather"``) concatenates the full table and
masks once — but scratch-row writes, the spec margin, and block
ownership are enforced BEFORE the kernel by the same width mask, so
swapping kernel bodies never changes which blocks are written or
which garbage is visible.

Cross-replica block migration (PR 13): because blocks are fixed-size,
refcounted, and layer-invariant, moving a live stream between replicas
is a BLOCK-TABLE REWRITE plus a bytes transfer — ``export_blocks``
gathers the named rows out of the per-layer device pools into one host
array (only the exported blocks cross d2h, never the pool), and
``import_blocks`` scatters them into freshly allocated rows on the
destination, whose pool/trie then adopt the refs through the normal
``alloc`` / ``PrefixCache.insert`` protocol.  ``payload_to_json`` /
``payload_from_json`` are the wire codec (base64 over the HTTP
transport).  The engine-side choreography — ring drain, slot freeze,
resume snapshot — lives in serving/engine.py (``migrate_out`` /
``migrate_in``); this module stays pure bytes + ids.

Quantized pools (PR 16, ``Engine(kv_dtype="int8")``): each per-layer
pool becomes a ``serving/quant.py`` ``QuantKV`` — int8 codes
``[num_blocks, block_size, H, hd]`` plus a PARALLEL SCALE POOL of
per-block per-head f32 dequant multipliers ``[num_blocks, H]``.  The
scale pool obeys three invariants on top of the protocol above:

* ONE scale row per physical block per layer per K/V — the scale is
  block metadata, indexed by the same layer-invariant block id as the
  codes, so nothing in BlockPool/PrefixCache changes (they track ids,
  not bytes).
* Scales TRAVEL WITH their block: copy-on-write copies the scale row
  alongside the code rows, and the migration wire carries both
  (``export_blocks`` returns ``(codes, scales)`` for quantized pools;
  ``import_blocks`` scatters both; the JSON codec base64s each).
* Shared blocks are never re-quantized: writes only land in a slot's
  own fresh blocks (the same full-block-adoption rule that makes cow
  degenerate to no-copy), so a block's scale is IMMUTABLE while its
  refcount is shared — adopters always read exactly the scale the
  producer wrote.

``import_blocks`` raises ``KVDtypeMismatch`` when the payload and the
destination pools disagree about quantization (codes into fp pools,
fp rows into quantized pools) BEFORE any geometry check — a
dtype-mismatched migration must adopt nothing, with a reason the
HTTP layer can surface machine-readably.

The invariant tests live in tests/test_kvcache.py (pool/trie),
tests/test_ragged_attn.py (kernel-side masking), and
tests/test_quant_serving.py (scale-pool parity + migration).
"""
from __future__ import annotations


class KVDtypeMismatch(ValueError):
    """Migration payload and destination pools disagree about KV
    quantization (int8 codes vs fp rows) — the import must adopt
    nothing.  Subclasses ValueError so pre-quantization callers that
    caught geometry errors keep working; the HTTP layer maps it to a
    machine-readable ``reason: "kv_dtype_mismatch"``."""


def _is_quant_pool(pool):
    return hasattr(pool, "codes") and hasattr(pool, "scale")


def export_blocks(k_pools, v_pools, block_ids):
    """Gather the device rows of ``block_ids`` from the engine's
    per-layer paged pools into ONE host array — the bytes half of a
    migration (``Engine.migrate_out`` wraps it with the request's
    resume snapshot).

    ``k_pools`` / ``v_pools``: per-layer pool arrays, each
    ``[num_blocks, block_size, H, hd]``.  ``block_ids``: the
    layer-invariant physical rows to export, in table order (a slot's
    FULL blocks only — the partial tail is recomputed by the
    destination's own prefill).  Returns a numpy array of shape
    ``(n_layers, 2, n_blocks, block_size, H, hd)`` with axis 1 = (K,
    V); the row indexing runs ON DEVICE so only the exported blocks
    cross the d2h boundary, never the whole pool.

    Quantized pools (``QuantKV``) return a ``(codes, scales)`` PAIR:
    the int8 codes in the shape above plus their per-block per-head
    scales ``(n_layers, 2, n_blocks, H)`` — scales travel with their
    blocks, in the same table order."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    ids = jnp.asarray([int(b) for b in block_ids], jnp.int32)
    if _is_quant_pool(k_pools[0]):
        codes = [jnp.stack((jnp.take(kp.codes, ids, axis=0),
                            jnp.take(vp.codes, ids, axis=0)))
                 for kp, vp in zip(k_pools, v_pools)]
        scales = [jnp.stack((jnp.take(kp.scale, ids, axis=0),
                             jnp.take(vp.scale, ids, axis=0)))
                  for kp, vp in zip(k_pools, v_pools)]
        return (np.asarray(jax.device_get(jnp.stack(codes))),
                np.asarray(jax.device_get(jnp.stack(scales))))
    parts = [jnp.stack((jnp.take(kp, ids, axis=0),
                        jnp.take(vp, ids, axis=0)))
             for kp, vp in zip(k_pools, v_pools)]
    return np.asarray(jax.device_get(jnp.stack(parts)))


def import_blocks(k_pools, v_pools, block_ids, data, scales=None):
    """Scatter an ``export_blocks`` array into rows ``block_ids`` of
    the destination's per-layer pools.  Returns new ``(k_pools,
    v_pools)`` lists — jax arrays are immutable, so the engine
    reassigns its pool references (safe between dispatches: the
    decode/prefill programs re-bind the pools at every dispatch).
    Raises ``KVDtypeMismatch`` when payload and pools disagree about
    quantization (checked FIRST — int8 codes must never be scattered
    into fp pools as if they were activations, nor fp rows adopted
    without scales), and plain ValueError when the geometry does not
    match (block size / heads / head_dim / layer count) — either way
    the caller rolls its fresh allocation back, adopting NOTHING.

    ``scales``: the per-block per-head scale array that
    ``export_blocks`` returned alongside quantized codes,
    ``(n_layers, 2, n_blocks, H)``; required iff the destination
    pools are ``QuantKV``."""
    import jax.numpy as jnp
    import numpy as np
    quant = _is_quant_pool(k_pools[0])
    if quant and scales is None:
        raise KVDtypeMismatch(
            "destination pools are int8-quantized (kv_dtype='int8') "
            "but the migration payload carries no scales — refusing "
            "to adopt fp rows into a quantized pool")
    if not quant and scales is not None:
        raise KVDtypeMismatch(
            "migration payload carries int8 codes + scales but the "
            "destination pools are fp (kv_dtype mismatch between "
            "peers) — refusing to adopt")
    data = np.asarray(data)
    ids = [int(b) for b in block_ids]
    want = (len(k_pools), 2, len(ids)) + tuple(k_pools[0].shape[1:])
    if tuple(data.shape) != want:
        raise ValueError(
            f"migration payload shape {tuple(data.shape)} does not "
            f"match destination pools (want {want}: layers x (K,V) x "
            "blocks x block_size x heads x head_dim)")
    idx = jnp.asarray(ids, jnp.int32)
    if quant:
        from .quant import QuantKV
        scales = np.asarray(scales)
        want_s = (len(k_pools), 2, len(ids), k_pools[0].shape[2])
        if tuple(scales.shape) != want_s:
            raise ValueError(
                f"migration scale shape {tuple(scales.shape)} does "
                f"not match destination scale pools (want {want_s}: "
                "layers x (K,V) x blocks x heads)")
        new_k, new_v = [], []
        for li, (kp, vp) in enumerate(zip(k_pools, v_pools)):
            new_k.append(QuantKV(
                kp.codes.at[idx].set(
                    jnp.asarray(data[li, 0], kp.codes.dtype)),
                kp.scale.at[idx].set(
                    jnp.asarray(scales[li, 0], kp.scale.dtype))))
            new_v.append(QuantKV(
                vp.codes.at[idx].set(
                    jnp.asarray(data[li, 1], vp.codes.dtype)),
                vp.scale.at[idx].set(
                    jnp.asarray(scales[li, 1], vp.scale.dtype))))
        return new_k, new_v
    new_k, new_v = [], []
    for li, (kp, vp) in enumerate(zip(k_pools, v_pools)):
        new_k.append(kp.at[idx].set(jnp.asarray(data[li, 0], kp.dtype)))
        new_v.append(vp.at[idx].set(jnp.asarray(data[li, 1], vp.dtype)))
    return new_k, new_v


def payload_to_json(payload):
    """JSON-encode a migration payload for the HTTP wire: the
    ``kv["data"]`` numpy array becomes base64 bytes + dtype + shape
    (``data_b64`` / ``data_dtype`` / ``data_shape``), and a quantized
    payload's ``kv["scales"]`` likewise (``scales_b64`` / ...) —
    scales travel with their blocks over the wire.  Everything else
    in the payload is already JSON-shaped.  ``payload_from_json``
    inverts exactly."""
    import base64
    import numpy as np
    out = {k: v for k, v in payload.items() if k != "kv"}
    kv = payload.get("kv")
    if kv is not None:
        kv = dict(kv)
        for field in ("data", "scales"):
            arr = kv.pop(field, None)
            if arr is not None:
                arr = np.ascontiguousarray(arr)
                kv[f"{field}_b64"] = base64.b64encode(
                    arr.tobytes()).decode("ascii")
                kv[f"{field}_dtype"] = str(arr.dtype)
                kv[f"{field}_shape"] = list(arr.shape)
        out["kv"] = kv
    return out


def payload_from_json(obj):
    """Decode a ``payload_to_json`` wire dict back into the in-memory
    payload form (``kv["data"]`` — and ``kv["scales"]`` for
    quantized payloads — as writable numpy arrays)."""
    import base64
    import numpy as np
    out = {k: v for k, v in obj.items() if k != "kv"}
    kv = obj.get("kv")
    if kv is not None:
        kv = dict(kv)
        for field in ("data", "scales"):
            b64 = kv.pop(f"{field}_b64", None)
            if b64 is not None:
                dtype = np.dtype(kv.pop(f"{field}_dtype"))
                shape = tuple(kv.pop(f"{field}_shape"))
                kv[field] = np.frombuffer(
                    base64.b64decode(b64),
                    dtype=dtype).reshape(shape).copy()
        out["kv"] = kv
    return out


def per_shard_block_bytes(block_size, num_heads, head_dim, dtype,
                          n_layers, mp=1, scale_dtype=None):
    """PER-SHARD HBM cost of ONE logical KV block across every layer:
    ``n_layers * 2 (K and V) * block_size * (num_heads/mp) * head_dim
    * itemsize``.  Under a tensor-parallel mesh (Engine(mesh=...))
    the pools shard on the head axis, so each device stores only its
    ``num_heads/mp`` heads' slice of every block — which is why a
    fixed per-chip budget (``Engine(kv_budget_mb=...)``) buys ``mp``x
    the logical blocks: KV capacity, the HBM ceiling on concurrent
    slots, scales with the mesh.  ``num_heads`` must divide by ``mp``
    (attention shards whole heads).

    ``dtype`` is the STORED row dtype — int8 for a quantized pool
    (``Engine(kv_dtype="int8")``), in which case ``scale_dtype``
    (f32) adds the parallel scale pool's ``n_layers * 2 *
    (num_heads/mp)`` per-block per-head multipliers, so the quoted
    cost is the block's TRUE footprint and the int8/f32 capacity
    ratio works out to ``4 / (1 + 4/(block_size*head_dim))`` (~3.8x
    for the small test geometries, ~4x at real ones) instead of a
    flattering byte-only 4x."""
    import numpy as np
    mp = int(mp)
    if mp < 1 or num_heads % mp:
        raise ValueError(
            f"num_heads ({num_heads}) must divide by mp ({mp})")
    total = (int(n_layers) * 2 * int(block_size) * (num_heads // mp)
             * int(head_dim) * np.dtype(dtype).itemsize)
    if scale_dtype is not None:
        total += (int(n_layers) * 2 * (num_heads // mp)
                  * np.dtype(scale_dtype).itemsize)
    return total


class NoFreeBlocks(RuntimeError):
    """The pool cannot satisfy an allocation (even after eviction)."""


def _as_ids(blocks):
    if isinstance(blocks, int):
        return (blocks,)
    return blocks


class BlockPool:
    """Fixed-size-block allocator over the engine's K/V pool rows.

    ``reserved_blocks`` low ids are never handed out — the engine pins
    row 0 as the scratch block that parked (inactive) slots harmlessly
    read and write through.

    ``fault_hook`` (optional): called with the request size before
    every ``alloc`` — the deterministic chaos harness
    (serving/faults.py) threads its "pool_exhaust" site through it,
    raising ``NoFreeBlocks`` on scheduled ticks so recovery paths are
    exercised against pool pressure that composes with other
    failures.  None (default) costs nothing.

    ``shards`` (data-parallel serving, ``Engine(mesh=(mp, dp))``): the
    pool rows divide into ``shards`` CONTIGUOUS equal ranges, one per
    'dp' mesh shard — shard ``d`` owns global rows ``[d*rps,
    (d+1)*rps)`` where ``rps = num_blocks // shards`` — and every
    range reserves its own ``reserved_blocks`` leading rows (shard
    ``d``'s scratch row is ``scratch_row(d) = d*rps``), so a parked
    slot's masked writes stay INSIDE its own shard's pool slice (the
    shard_map kernel instance cannot address another shard's rows).
    ``alloc(n, shard=d)`` draws only from shard ``d``'s free list and
    ``decref`` returns a freed block to its OWN shard; a block never
    migrates between shards because the device pool is physically
    split at exactly these row boundaries.  ``shards=1`` (default) is
    bit-identical to the unsharded pool.
    """

    def __init__(self, num_blocks, block_size, reserved_blocks=0,
                 fault_hook=None, shards=1):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        shards = int(shards)
        if shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        if num_blocks % shards:
            raise ValueError(
                f"num_blocks ({num_blocks}) must divide into {shards} "
                "equal dp shard ranges")
        rps = num_blocks // shards
        if rps - reserved_blocks < 1:
            raise ValueError(
                f"pool needs at least one allocatable block per shard "
                f"({num_blocks} total / {shards} shard(s), "
                f"{reserved_blocks} reserved each)")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.reserved_blocks = int(reserved_blocks)
        self.shards = shards
        self.rows_per_shard = rps
        # pop() from the tail hands out low ids first (stable tests)
        self._free = [list(range(d * rps + rps - 1,
                                 d * rps + reserved_blocks - 1, -1))
                      for d in range(shards)]
        self._ref = [0] * self.num_blocks
        self._fault_hook = fault_hook

    @property
    def managed_blocks(self):
        return self.num_blocks - self.shards * self.reserved_blocks

    def shard_of(self, block):
        """The dp shard whose pool range holds global row ``block``."""
        return int(block) // self.rows_per_shard

    def scratch_row(self, shard=0):
        """Global row id of ``shard``'s reserved scratch block (the
        first row of its range) — parked slots' tables point here."""
        if not self.reserved_blocks:
            raise ValueError("pool has no reserved scratch rows")
        return int(shard) * self.rows_per_shard

    def free_count(self, shard=None):
        if shard is None:
            return sum(len(f) for f in self._free)
        return len(self._free[shard])

    def in_use(self):
        return self.managed_blocks - self.free_count()

    def refcount(self, block):
        return self._ref[block]

    def alloc(self, n, shard=0):
        """Take ``n`` blocks off ``shard``'s free list at refcount 1."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if self._fault_hook is not None:
            self._fault_hook(n)  # chaos harness: may raise NoFreeBlocks
        free = self._free[shard]
        if n > len(free):
            raise NoFreeBlocks(
                f"need {n} blocks, only {len(free)} free of "
                f"{self.managed_blocks // self.shards} on dp shard "
                f"{shard} (evict cached prefixes first)")
        out = [free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def incref(self, blocks):
        for b in _as_ids(blocks):
            if self._ref[b] < 1:
                raise RuntimeError(
                    f"incref on free block {b} — a reference can only "
                    "be shared from a live one")
            self._ref[b] += 1

    def decref(self, blocks):
        """Drop one reference per block; blocks reaching refcount 0
        return to the free list.  Returns the freed ids."""
        freed = []
        for b in _as_ids(blocks):
            if self._ref[b] < 1:
                raise RuntimeError(f"double free of block {b}")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                self._free[self.shard_of(b)].append(b)
                freed.append(b)
        return freed

    def cow(self, block):
        """Copy-on-write: make the caller's reference to ``block``
        privately writable.  Sole owner -> the block itself (no copy).
        Shared -> the caller's ref moves to a fresh block and the
        caller must copy the device rows; returns ``(writable_block,
        needs_copy)``.  Raises NoFreeBlocks with the original ref
        intact if the pool is empty (evict, then retry).

        The serving engine adopts cached prefixes at FULL-block
        granularity and writes only into freshly allocated blocks, so
        its steady state never needs the copy — this is the general
        primitive (partial-block adoption, future mutation paths).
        """
        if self._ref[block] < 1:
            raise RuntimeError(f"cow of free block {block}")
        if self._ref[block] == 1:
            return block, False
        # before decref: failure leaves the shared ref untouched; the
        # replacement comes from the block's OWN shard range
        new = self.alloc(1, shard=self.shard_of(block))[0]
        self._ref[block] -= 1
        return new, True


class _TrieNode:
    __slots__ = ("key", "block", "parent", "children", "last_used")

    def __init__(self, key, block, parent, last_used):
        self.key = key
        self.block = block
        self.parent = parent
        self.children = {}
        self.last_used = last_used


class PrefixCache:
    """Token-trie over FULL blocks of previously-seen prompts.

    Each node covers one block's worth of token ids; node depth i
    means "positions [i*bs, (i+1)*bs) of some prompt", and its block
    holds the K/V computed for exactly that token prefix — so an
    adopter walking the trie from the root gets blocks whose content
    is what its own prefill would have produced for the shared span.
    Partial blocks are never cached (the engine trims matches to block
    boundaries), which keeps adoption pure sharing: writes always land
    in the adopter's own fresh blocks (``BlockPool.cow`` degenerates
    to the no-copy case).

    ``evict_hook`` (optional): called as ``hook(tokens, block)`` for
    every node ``evict`` is about to drop, BEFORE the pool reference
    — ``tokens`` is the node's full token prefix (root through the
    dying block, reconstructed from the parent chain), so the hook can
    demote the block's device rows to a content-addressed host tier
    (serving/offload.py) while they are still resident.  Exceptions
    are swallowed: a failed demote must free the block normally, never
    wedge eviction mid-walk (``clear`` — the engine-reset path whose
    device pools may already be gone — never calls it).

    Data-parallel pools (``BlockPool(shards=dp)``) get ONE TRIE PER
    SHARD: a slot can only gather blocks inside its own dp shard's
    pool range, so a cached prefix is only adoptable by slots of the
    shard that computed it.  ``match(tokens, shard=d)`` walks shard
    ``d``'s trie; ``match(tokens)`` (shard=None) probes every shard
    and adopts from the one with the longest cached span (the
    cross-shard lookup the prefix-warm service uses).  ``insert``
    routes to the trie of the shard that owns ``blocks[0]``.
    """

    def __init__(self, pool, evict_hook=None):
        self.pool = pool
        self.block_size = pool.block_size
        self.evict_hook = evict_hook
        # one root per dp pool shard: key tuple -> _TrieNode
        self._roots = [dict()
                       for _ in range(getattr(pool, "shards", 1))]
        self._clock = 0       # LRU stamp (monotonic counter)

    def _tick(self):
        self._clock += 1
        return self._clock

    @staticmethod
    def _iter_root(root):
        stack = list(root.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            yield node

    def _iter_nodes(self):
        for root in self._roots:
            yield from self._iter_root(root)

    def cached_blocks(self):
        return sum(1 for _ in self._iter_nodes())

    def _walk(self, tokens, root, limit, stamp=None):
        blocks = []
        children = root
        for i in range(limit):
            key = tuple(int(x) for x in
                        tokens[i * self.block_size:
                               (i + 1) * self.block_size])
            node = children.get(key)
            if node is None:
                break
            if stamp is not None:
                node.last_used = stamp
            blocks.append(node.block)
            children = node.children
        return blocks

    def match(self, tokens, shard=None):
        """Longest cached prefix of ``tokens`` in full blocks, capped
        so at least ONE token is left for the adopter's own prefill
        (admission still needs a last-position logit to sample from).
        Takes one pool reference per returned block on behalf of the
        caller — release with ``pool.decref`` at slot eviction.
        ``shard`` names the dp shard whose trie to walk (the adopting
        slot's); None probes every shard and adopts from the longest.
        Returns ``(block_ids, matched_token_count)``."""
        limit = (len(tokens) - 1) // self.block_size
        if shard is None:
            shard = 0
            if len(self._roots) > 1:
                shard = max(
                    range(len(self._roots)),
                    key=lambda d: len(self._walk(tokens,
                                                 self._roots[d],
                                                 limit)))
        blocks = self._walk(tokens, self._roots[shard], limit,
                            stamp=self._tick())
        self.pool.incref(blocks)
        return blocks, len(blocks) * self.block_size

    def insert(self, tokens, blocks):
        """Register ``blocks[i]`` as the cached K/V of ``tokens``'s
        i-th FULL block.  Existing nodes win (a duplicate block —
        two same-prefix requests prefilled in the same tick — stays
        slot-private and frees at eviction); each NEW node takes the
        cache's own pool reference.  The target trie is the one of
        the dp shard that owns the blocks (all of one slot's blocks
        live in one shard range by construction)."""
        bs = self.block_size
        if not blocks:
            return
        children = self._roots[self.pool.shard_of(blocks[0])
                               if len(self._roots) > 1 else 0]
        parent = None
        t = self._tick()
        n = min(len(blocks), len(tokens) // bs)
        for i in range(n):
            key = tuple(int(x) for x in tokens[i * bs:(i + 1) * bs])
            node = children.get(key)
            if node is None:
                node = _TrieNode(key, blocks[i], parent, t)
                self.pool.incref(blocks[i])
                children[key] = node
            node.last_used = t
            parent = node
            children = node.children

    @staticmethod
    def _prefix_of(node):
        """The full token prefix ``node``'s block encodes — every
        ancestor's key plus its own, root-first — i.e. the content a
        demote hook must hash to address the block."""
        keys = []
        while node is not None:
            keys.append(node.key)
            node = node.parent
        out = []
        for key in reversed(keys):
            out.extend(key)
        return tuple(out)

    def evict(self, n, shard=None):
        """Free at least ``n`` blocks by dropping least-recently-used
        UNREFERENCED cached prefixes, deepest first (a node with live
        children or an active adopter — pool refcount > 1 — is never
        evicted; evicting a leaf exposes its parent as the next
        candidate).  One trie walk + a heap, not a rescan per freed
        block — eviction runs inside the engine's step loop and must
        not stall decode ticks under sustained pressure.  ``shard``
        restricts the walk to one dp shard's trie (pressure on shard
        ``d`` can only be relieved by shard ``d``'s blocks); None
        evicts across all shards.  Returns the freed block ids (may
        be shorter than ``n`` when nothing evictable remains)."""
        import heapq
        freed = []
        roots = (self._roots if shard is None
                 else [self._roots[shard]])
        heap = [(node.last_used, id(node), node, root)
                for root in roots
                for node in self._iter_root(root)
                if not node.children
                and self.pool.refcount(node.block) == 1]
        heapq.heapify(heap)
        while heap and len(freed) < n:
            _, _, node, root = heapq.heappop(heap)
            if node.children or self.pool.refcount(node.block) != 1:
                continue              # state changed since enqueue
            owner = (node.parent.children if node.parent else root)
            if owner.get(node.key) is not node:
                continue              # already detached
            owner.pop(node.key)
            if self.evict_hook is not None:
                try:
                    self.evict_hook(self._prefix_of(node), node.block)
                except Exception:
                    pass  # failed demote: free normally, never wedge
            freed.extend(self.pool.decref(node.block))
            parent = node.parent
            if parent is not None and not parent.children \
                    and self.pool.refcount(parent.block) == 1:
                heapq.heappush(
                    heap,
                    (parent.last_used, id(parent), parent, root))
        return freed

    def clear(self):
        """Drop every cached prefix (engine reset); returns freed ids."""
        freed = []
        for node in list(self._iter_nodes()):
            freed.extend(self.pool.decref(node.block))
        self._roots = [dict() for _ in self._roots]
        return freed
