"""Serving requests and the admission queue.

A ``Request`` is one generation job: prompt ids in, generated ids out,
with a threading.Event completion handle so HTTP handler threads (or
any caller thread) can block on ``result()`` while the engine thread
decodes.  The ``RequestQueue`` is the admission buffer in front of the
slot pool — FIFO with per-request deadlines, so a request that waits
longer than its ``timeout`` is failed loudly instead of silently
decoding after its caller gave up (the reference's closest analogue is
the PS heartbeat monitor's lost-worker accounting; here the lost party
is a request, not a worker).
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque

import numpy as np


class RequestTimeout(RuntimeError):
    """The request exceeded its queue deadline before a slot freed up."""


# Largest admissible sampling seed (exclusive): the device sampling key
# derivation packs the seed into two 32-bit words (lo | hi << 32, hi
# folded into a jax.random key — core/rng.request_key), so a seed must
# be a non-negative int below 2**63; the host rng path rejects
# negatives anyway, so submit() enforces one bound for both modes.
MAX_SEED = 2 ** 63


class QueueFull(RuntimeError):
    """The admission queue is at max_queue; shed load at the edge."""


_req_ids = itertools.count()


class Request:
    """One generation job moving through queue -> slot -> done."""

    def __init__(self, prompt, max_new_tokens, eos_token_id=None,
                 timeout=None, temperature=1.0, top_k=0, top_p=1.0,
                 seed=None):
        self.id = next(_req_ids)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = int(top_k or 0)
        self.top_p = float(top_p)
        self.seed = seed
        self.generated = []          # ints, appended by the engine
        self.submitted_at = time.monotonic()
        self.deadline = (self.submitted_at + float(timeout)
                         if timeout is not None else None)
        self.first_token_at = None   # TTFT anchor
        self.finished_at = None
        self.error = None
        self._done = threading.Event()

    @property
    def do_sample(self):
        return (self.top_k > 0 or self.temperature != 1.0
                or self.top_p < 1.0)

    @property
    def sample_seed(self):
        """Effective sampling seed: the submitted seed, or the request
        id when none was given (reproducible across engine restarts
        only for explicit seeds — ids are a process-global counter)."""
        return self.seed if self.seed is not None else self.id

    def seed_words(self):
        """(lo, hi) 32-bit words of the effective seed — the transport
        format of the device sampling key derivation (jax without x64
        cannot carry an int64 seed; core/rng.request_key folds the
        words back into one key)."""
        s = self.sample_seed
        return s & 0xFFFFFFFF, (s >> 32) & 0xFFFFFFFF

    def expired(self, now=None):
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) > self.deadline

    # -- engine side -----------------------------------------------------
    def _finish(self, error=None):
        self.error = error
        self.finished_at = time.monotonic()
        self._done.set()

    # -- caller side -----------------------------------------------------
    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until the engine finishes this request; returns the
        full id sequence (prompt + generated) as int32 numpy.  Raises
        the engine-recorded error (e.g. RequestTimeout) on failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id}: no result after {timeout}s "
                "(engine not stepping?)")
        if self.error is not None:
            raise self.error
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])

    def __repr__(self):
        state = ("error" if self.error else
                 "done" if self.done() else "pending")
        return (f"Request(id={self.id}, prompt_len={len(self.prompt)}, "
                f"generated={len(self.generated)}, {state})")


class RequestQueue:
    """Thread-safe FIFO admission queue with deadline enforcement."""

    def __init__(self, max_queue=0):
        self.max_queue = int(max_queue)  # 0 = unbounded
        self._lock = threading.Lock()
        self._q = deque()

    def put(self, req):
        with self._lock:
            if self.max_queue and len(self._q) >= self.max_queue:
                raise QueueFull(
                    f"admission queue full ({self.max_queue}); request "
                    f"{req.id} shed at the edge")
            self._q.append(req)

    def push_front(self, req):
        """Return a popped-but-not-admitted request to the queue HEAD
        (the scheduler's gate declined it — e.g. no KV blocks free);
        FIFO order is preserved.  Exempt from max_queue: the request
        already held a queue place (a concurrent put may briefly
        overshoot the bound by one)."""
        with self._lock:
            self._q.appendleft(req)

    def pop_ready(self, now=None):
        """Pop the next request that has not expired; expired requests
        are failed in place (RequestTimeout) and returned via the
        second element so the caller can count them.

        Returns (request | None, list_of_timed_out_requests).
        """
        now = time.monotonic() if now is None else now
        timed_out = []
        with self._lock:
            while self._q:
                req = self._q.popleft()
                if req.expired(now):
                    req._finish(RequestTimeout(
                        f"request {req.id} spent "
                        f"{now - req.submitted_at:.3f}s queued, over its "
                        f"{req.deadline - req.submitted_at:.3f}s timeout"))
                    timed_out.append(req)
                    continue
                return req, timed_out
        return None, timed_out

    def expire(self, now=None):
        """Sweep out every expired request (full-pool case: nothing is
        being popped, but deadlines must still fire).  Returns the
        timed-out requests, already failed."""
        now = time.monotonic() if now is None else now
        with self._lock:
            live, timed_out = [], []
            for req in self._q:
                (timed_out if req.expired(now) else live).append(req)
            self._q = deque(live)
        for req in timed_out:
            req._finish(RequestTimeout(
                f"request {req.id} spent {now - req.submitted_at:.3f}s "
                f"queued, over its "
                f"{req.deadline - req.submitted_at:.3f}s timeout"))
        return timed_out

    def depth(self):
        with self._lock:
            return len(self._q)

    def pending(self):
        """Snapshot of the queued requests in FIFO order (the
        ``/debug/requests`` surface; the queue keeps its entries)."""
        with self._lock:
            return list(self._q)

    def drain(self, error=None):
        """Fail every queued request (engine shutdown)."""
        with self._lock:
            pending = list(self._q)
            self._q.clear()
        for req in pending:
            req._finish(error or RuntimeError("engine stopped"))
        return pending
