"""Serving requests and the admission queue.

A ``Request`` is one generation job: prompt ids in, generated ids out,
with a threading.Event completion handle so HTTP handler threads (or
any caller thread) can block on ``result()`` while the engine thread
decodes.  The ``RequestQueue`` is the admission buffer in front of the
slot pool; it orders service by PRIORITY CLASS (strict tiers — an
interactive request never waits behind a batch job) and, within a
tier, by WEIGHTED-FAIR share across tenants (start-time fair queuing
over token cost, so a flooding tenant cannot starve another past its
configured weight), while still enforcing per-request deadlines: a
request that waits longer than its ``timeout`` is failed loudly
instead of silently decoding after its caller gave up.

Load-shedding vocabulary (the overload-protection edge): every
rejection carries an honest ``retry_after`` hint —

* ``QueueFull``     — the admission queue is at ``max_queue``.
* ``RateLimited``   — the tenant's token bucket is empty
  (``TenantPolicy(rate=...)``).
* ``DeadlineShed``  — the estimated queue-drain time already blows the
  request's deadline, so admitting it would only burn slot time on a
  result nobody is still waiting for.

Preemption support: the engine may REQUEUE a running request under
priority pressure (``requeue()`` — it re-enters at the head of its own
lane, its fairness cost already charged).  The request keeps its
emitted tokens; ``context`` is the frozen prompt+emitted snapshot a
re-admission must prefill so the resumed stream continues exactly
where it stopped.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque

import numpy as np


class RequestTimeout(RuntimeError):
    """The request exceeded its queue deadline before a slot freed up."""


class Rejected(RuntimeError):
    """Base of the submit-time load-shedding rejections; carries the
    honest backoff hint (``retry_after`` seconds, None when the edge
    has no estimate)."""

    def __init__(self, msg, retry_after=None):
        super().__init__(msg)
        self.retry_after = retry_after


class QueueFull(Rejected):
    """The admission queue is at max_queue; shed load at the edge."""


class RateLimited(Rejected):
    """The tenant's token bucket cannot cover this request's cost."""


class DeadlineShed(Rejected):
    """The estimated queue-drain time already exceeds the request's
    deadline — admitted, it would time out anyway; shed at submit with
    a computed Retry-After instead."""


# Largest admissible sampling seed (exclusive): the device sampling key
# derivation packs the seed into two 32-bit words (lo | hi << 32, hi
# folded into a jax.random key — core/rng.request_key), so a seed must
# be a non-negative int below 2**63; the host rng path rejects
# negatives anyway, so submit() enforces one bound for both modes.
MAX_SEED = 2 ** 63

DEFAULT_TENANT = "default"

_req_ids = itertools.count()


class TenantPolicy:
    """Per-tenant admission policy.

    weight : weighted-fair share of queue service within a priority
        tier (tokens served in proportion ``weight / sum(weights of
        backlogged tenants)``).
    rate : token-bucket refill in tokens/sec charged at submit
        (``prompt + max_new_tokens`` per request); None = unlimited.
    burst : bucket depth in tokens (default ``4 * rate`` — one burst
        of a few requests rides through, sustained traffic is held to
        ``rate``).  Requires ``rate``.
    """

    __slots__ = ("weight", "rate", "burst")

    def __init__(self, weight=1.0, rate=None, burst=None):
        weight = float(weight)
        if weight <= 0:
            raise ValueError(f"weight must be > 0, got {weight}")
        if rate is not None and float(rate) <= 0:
            raise ValueError(f"rate must be > 0 tokens/sec, got {rate}")
        if burst is not None and rate is None:
            raise ValueError("burst requires rate (it is the bucket "
                             "depth of the rate limiter)")
        self.weight = weight
        self.rate = None if rate is None else float(rate)
        self.burst = (None if self.rate is None
                      else float(burst) if burst is not None
                      else 4.0 * self.rate)


class TokenBucket:
    """Classic token bucket (tokens/sec refill, bounded depth) — the
    per-tenant rate limiter consulted at ``Engine.submit``.  Thread
    safe: submits arrive from HTTP handler threads."""

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def take(self, cost, now=None):
        """Consume ``cost`` tokens.  Returns None on success, else the
        seconds until the bucket could cover the cost (the honest
        Retry-After)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last)
                               * self.rate)
            self._last = now
            if cost <= self._tokens:
                self._tokens -= cost
                return None
            return (cost - self._tokens) / self.rate

    def refund(self, cost):
        """Return a charge taken for a request that was then rejected
        for an unrelated reason (queue full, deadline shed): the
        request did no work, so it must not count against the rate —
        otherwise one shedding class cascades into RateLimited
        lockout."""
        with self._lock:
            self._tokens = min(self.burst, self._tokens + cost)


class Request:
    """One generation job moving through queue -> slot -> done."""

    def __init__(self, prompt, max_new_tokens, eos_token_id=None,
                 timeout=None, temperature=1.0, top_k=0, top_p=1.0,
                 seed=None, priority=0, tenant=None, adapter=None):
        self.id = next(_req_ids)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.prompt = prompt
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.temperature = float(temperature)
        self.top_k = int(top_k or 0)
        self.top_p = float(top_p)
        self.seed = seed
        self.priority = int(priority)   # higher = more urgent; the
        #   scheduler may PREEMPT a running lower-priority request to
        #   admit this one
        self.tenant = (DEFAULT_TENANT if tenant is None
                       else str(tenant))
        self.adapter = None if adapter is None else str(adapter)
        self._adapter_id = 0         # LoRA lane (0 = base model);
        #   resolved by Engine.submit against its adapter registry
        self.generated = []          # ints, appended by the engine
        # Streaming sinks: TokenStream consumers attached by the HTTP
        # edge (or any caller).  The lock makes append+fan-out vs
        # attach-with-replay atomic, so a sink attached between two
        # emits sees every token exactly once.  _finish_cbs fire once
        # on completion (adapter unpin, server-side accounting).
        self._sink_lock = threading.Lock()
        self._sinks = []
        self._finish_cbs = []
        self.submitted_at = time.monotonic()
        self.deadline = (self.submitted_at + float(timeout)
                         if timeout is not None else None)
        self.first_token_at = None   # TTFT anchor
        self.finished_at = None
        self.error = None
        self.preemptions = 0         # times evicted mid-stream and
        self._ctx = None             # requeued; the frozen resume
        #   context (prompt + emitted-so-far) a re-admission prefills
        self._fair_charged = False   # weighted-fair cost charged once
        #   at first pop; a preempted requeue must not pay twice
        self._done = threading.Event()

    @property
    def do_sample(self):
        return (self.top_k > 0 or self.temperature != 1.0
                or self.top_p < 1.0)

    @property
    def context(self):
        """Token ids a (re)admission must prefill: the prompt, or —
        after a preemption — the frozen prompt + emitted-so-far
        snapshot, so the resumed stream continues from exactly the
        state the eviction interrupted (frozen at preemption time;
        tokens emitted after resume do not grow it)."""
        return self._ctx if self._ctx is not None else self.prompt

    @property
    def remaining(self):
        """Tokens this request may still emit (its share of queue
        drain work)."""
        return max(self.max_new_tokens - len(self.generated), 0)

    @property
    def cost_tokens(self):
        """Slot work the request still represents: context to prefill
        plus tokens left to decode — the unit of fairness charging,
        backlog estimates, and token buckets."""
        return len(self.context) + self.remaining

    @property
    def sample_seed(self):
        """Effective sampling seed: the submitted seed, or the request
        id when none was given (reproducible across engine restarts
        only for explicit seeds — ids are a process-global counter)."""
        return self.seed if self.seed is not None else self.id

    def seed_words(self):
        """(lo, hi) 32-bit words of the effective seed — the transport
        format of the device sampling key derivation (jax without x64
        cannot carry an int64 seed; core/rng.request_key folds the
        words back into one key)."""
        s = self.sample_seed
        return s & 0xFFFFFFFF, (s >> 32) & 0xFFFFFFFF

    def expired(self, now=None):
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) > self.deadline

    # -- engine side -----------------------------------------------------
    def _emit_token(self, tok):
        """Record one generated token and fan it out to any attached
        streams — atomically, so a stream attaching concurrently
        replays exactly the tokens it will not be fed live."""
        with self._sink_lock:
            self.generated.append(tok)
            idx = len(self.generated) - 1
            for s in self._sinks:
                s.feed(tok, idx)

    def _finish(self, error=None):
        self.error = error
        self.finished_at = time.monotonic()
        with self._sink_lock:
            sinks, self._sinks = self._sinks, []
            cbs, self._finish_cbs = self._finish_cbs, []
        self._done.set()
        for s in sinks:
            s.close(error)
        for cb in cbs:
            try:
                cb(self)
            except Exception:
                pass  # completion hooks must not mask the result

    # -- caller side -----------------------------------------------------
    def done(self):
        return self._done.is_set()

    def result(self, timeout=None):
        """Block until the engine finishes this request; returns the
        full id sequence (prompt + generated) as int32 numpy.  Raises
        the engine-recorded error (e.g. RequestTimeout) on failure."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.id}: no result after {timeout}s "
                "(engine not stepping?)")
        if self.error is not None:
            raise self.error
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])

    def __repr__(self):
        state = ("error" if self.error else
                 "done" if self.done() else "pending")
        return (f"Request(id={self.id}, prompt_len={len(self.prompt)}, "
                f"generated={len(self.generated)}, "
                f"priority={self.priority}, tenant={self.tenant!r}, "
                f"{state})")


class RequestQueue:
    """Thread-safe admission queue: strict priority tiers, weighted-
    fair tenant service within a tier, per-request deadlines.

    Ordering = start-time fair queuing (SFQ) over token cost: each
    tenant carries a virtual finish tag; popping serves, within the
    highest backlogged priority tier, the tenant whose virtual START
    (max of the global virtual clock and its own finish tag) is
    smallest, then advances that tenant's tag by ``cost / weight``.
    Equal weights degrade to round-robin by token volume; a tenant
    with weight w gets a w-proportional share of service while
    backlogged and banks nothing while idle (the max() forfeits
    unused virtual time).  Within one tenant+priority lane, order is
    FIFO.  All-default traffic (one tenant, one priority) behaves
    exactly like the old FIFO queue.
    """

    def __init__(self, max_queue=0, weights=None):
        self.max_queue = int(max_queue)  # 0 = unbounded
        self._lock = threading.Lock()
        # priority -> tenant -> deque of requests (FIFO per lane)
        self._tiers = {}
        self._n = 0
        self._backlog = {}  # priority -> queued token total, kept
        #   incrementally: backlog_tokens() runs on the SUBMIT hot
        #   path (deadline shedding), so it must not walk a deep
        #   queue under the lock the engine's admission also needs
        self._weights = dict(weights or {})
        self._vclock = 0.0
        self._vfin = {}   # tenant -> virtual finish tag

    def _weight(self, tenant):
        return float(self._weights.get(tenant, 1.0))

    def _lane(self, req):
        tier = self._tiers.setdefault(req.priority, {})
        return tier.setdefault(req.tenant, deque())

    def _prune(self, pri, tenant):
        tier = self._tiers.get(pri)
        if tier is None:
            return
        lane = tier.get(tenant)
        if lane is not None and not lane:
            del tier[tenant]
        if not tier:
            del self._tiers[pri]

    def _add_backlog_locked(self, req):
        # cost is frozen while queued (generated only grows in a
        # slot), so charge once on entry and release the SAME number
        # on exit — _queued_cost remembers it across the stay
        req._queued_cost = req.cost_tokens
        self._backlog[req.priority] = (
            self._backlog.get(req.priority, 0) + req._queued_cost)

    def _sub_backlog_locked(self, req):
        left = (self._backlog.get(req.priority, 0)
                - getattr(req, "_queued_cost", 0))
        if left > 0:
            self._backlog[req.priority] = left
        else:
            self._backlog.pop(req.priority, None)

    def put(self, req):
        with self._lock:
            if self.max_queue and self._n >= self.max_queue:
                raise QueueFull(
                    f"admission queue full ({self.max_queue}); request "
                    f"{req.id} shed at the edge")
            self._lane(req).append(req)
            self._n += 1
            self._add_backlog_locked(req)

    def requeue(self, req):
        """Return a popped request to the HEAD of its own lane — the
        gate-declined and PREEMPTION paths (the request already held a
        queue place, so this is exempt from max_queue; its fairness
        cost stays charged, so a resumed request is not billed twice).
        """
        with self._lock:
            self._lane(req).appendleft(req)
            self._n += 1
            self._add_backlog_locked(req)

    # old name, same contract (scheduler gate-decline path)
    push_front = requeue

    def _select_locked(self):
        """(pri, tenant, lane) of the next lane to serve, or None."""
        if not self._tiers:
            return None
        pri = max(self._tiers)
        tier = self._tiers[pri]
        best = None
        for tenant, lane in tier.items():
            if not lane:
                continue
            start = max(self._vclock, self._vfin.get(tenant, 0.0))
            key = (start, tenant)
            if best is None or key < best[0]:
                best = (key, tenant, lane, start)
        if best is None:
            # empty lanes only (pruned lazily): drop and retry
            self._tiers.pop(pri)
            return self._select_locked()
        _, tenant, lane, start = best
        return pri, tenant, lane, start

    def _charge_locked(self, req, start):
        self._vclock = start
        if not req._fair_charged:
            req._fair_charged = True
            self._vfin[req.tenant] = start + (req.cost_tokens
                                              / self._weight(req.tenant))
        self._prune_vfin_locked()

    def _prune_vfin_locked(self):
        """Bound the finish-tag map: tenant names arrive from the
        network edge, so it must not grow with every name ever seen.
        A tag is droppable once its tenant has nothing queued and the
        tag sits at or behind the virtual clock — ``max(vclock, tag)``
        would reproduce it as ``vclock`` anyway, so dropping it
        changes no scheduling decision."""
        if len(self._vfin) <= 128:
            return
        queued = set()
        for tier in self._tiers.values():
            queued.update(tier)
        for t in [t for t, v in self._vfin.items()
                  if t not in queued and v <= self._vclock]:
            del self._vfin[t]
        if len(self._vfin) > 256:
            # drive-by regime (a flood of one-shot tenant names can
            # stall the virtual clock, so the tag-behind-clock rule
            # above never fires): drop EVERY idle tenant's tag.  An
            # idle flow resetting its tag is standard SFQ semantics —
            # it forfeits banked debt exactly like it forfeits banked
            # credit — and a backlogged tenant is never touched.
            for t in [t for t in self._vfin if t not in queued]:
                del self._vfin[t]

    def pop_ready(self, now=None):
        """Pop the next request in service order that has not expired;
        expired requests are failed in place (RequestTimeout) and
        returned via the second element so the caller can count them.

        Returns (request | None, list_of_timed_out_requests).
        """
        now = time.monotonic() if now is None else now
        timed_out = []
        with self._lock:
            while True:
                sel = self._select_locked()
                if sel is None:
                    return None, timed_out
                pri, tenant, lane, start = sel
                req = lane.popleft()
                self._n -= 1
                self._sub_backlog_locked(req)
                self._prune(pri, tenant)
                if req.expired(now):
                    req._finish(RequestTimeout(
                        f"request {req.id} spent "
                        f"{now - req.submitted_at:.3f}s queued, over "
                        f"its "
                        f"{req.deadline - req.submitted_at:.3f}s "
                        "timeout"))
                    timed_out.append(req)
                    continue
                self._charge_locked(req, start)
                return req, timed_out

    def expire(self, now=None):
        """Sweep out every expired request (full-pool case: nothing is
        being popped, but deadlines must still fire).  Returns the
        timed-out requests, already failed."""
        now = time.monotonic() if now is None else now
        timed_out = []
        with self._lock:
            for pri, tier in list(self._tiers.items()):
                for tenant, lane in list(tier.items()):
                    live = deque(r for r in lane if not r.expired(now))
                    timed_out.extend(r for r in lane if r.expired(now))
                    if live:
                        tier[tenant] = live
                    else:
                        del tier[tenant]
                if not tier:
                    del self._tiers[pri]
            self._n -= len(timed_out)
            for req in timed_out:
                self._sub_backlog_locked(req)
        for req in timed_out:
            req._finish(RequestTimeout(
                f"request {req.id} spent {now - req.submitted_at:.3f}s "
                f"queued, over its "
                f"{req.deadline - req.submitted_at:.3f}s timeout"))
        return timed_out

    def depth(self):
        with self._lock:
            return self._n

    def best_priority(self):
        """Highest priority among queued requests (None when empty) —
        the engine's preemption probe."""
        with self._lock:
            return max(self._tiers) if self._tiers else None

    def backlog_tokens(self, min_priority=None):
        """Queued work in tokens (context + remaining decode), summed
        over requests at ``min_priority`` or above (all when None) —
        the deadline-shedding drain estimate's numerator.  O(distinct
        priorities), not O(depth): the totals are kept incrementally
        so the submit hot path never walks a deep queue under the
        lock the engine's admission needs."""
        with self._lock:
            return sum(v for pri, v in self._backlog.items()
                       if min_priority is None or pri >= min_priority)

    def pending(self):
        """Snapshot of the queued requests in approximate service
        order — priority tiers descending, tenants grouped, FIFO
        within a lane (the ``/debug/requests`` surface; the queue
        keeps its entries)."""
        with self._lock:
            out = []
            for pri in sorted(self._tiers, reverse=True):
                for tenant in sorted(self._tiers[pri]):
                    out.extend(self._tiers[pri][tenant])
        return out

    def drain(self, error=None):
        """Fail every queued request (engine shutdown)."""
        with self._lock:
            pending = [r for pri in self._tiers
                       for lane in self._tiers[pri].values()
                       for r in lane]
            self._tiers = {}
            self._n = 0
            self._backlog = {}
        for req in pending:
            req._finish(error or RuntimeError("engine stopped"))
        return pending
