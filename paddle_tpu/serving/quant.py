"""Quantized serving — int8 weight codes and int8 KV block pools.

KV bytes are the HBM ceiling on concurrent slots (every block held is
a block another request cannot reserve) and weight bytes bound
steady-state decode throughput, yet the quantization package
(quantization/weight_only.py, quantization/int8.py) never reached the
serving Engine.  This module is the bridge, in two independent halves:

* ``Engine(weight_dtype="int8")`` relayouts the serving checkpoint
  through weight-only int8 (``relayout_weights_int8``): every
  transformer-block Linear becomes a ``WeightOnlyInt8Linear`` whose
  int8 codes + per-output-channel f32 scales are registered BUFFERS —
  so they ride the engine's ``b_list`` into every compiled hot path
  (fused decode, fused spec-verify, paged chunk prefill, the ragged
  Pallas window) as live traced arrays, exactly as sampling params
  do.  No retracing, one program per config; the dequant sits
  adjacent to each matmul so XLA folds it into the operand read
  (the Tensor Processing Primitives framing: quantize/dequantize as
  fusable per-block primitives, never a whole-tensor pre-pass).

* ``Engine(kv_dtype="int8")`` stores the paged K/V pools as int8
  codes with a PER-BLOCK PER-HEAD f32 scale in a parallel scale pool
  (``QuantKV``): quantization happens at block write inside the
  dispatch (``paged_insert`` — a touched-block read-modify-write),
  dequantization at gather adjacent to the attention contraction
  (``paged_gather`` / the scale-aware ragged kernel), and the whole
  pool is NEVER dequantized at once — the Ragged Paged Attention
  motivation for keeping the gather math dtype-aware.  One logical
  block costs ``bs*H*hd`` code bytes + ``H`` scale floats instead of
  ``bs*H*hd`` f32s, so the same ``kv_budget_mb`` holds ~4x the
  blocks on f32 checkpoints (~2x vs bf16), compounding with mesh
  sharding (mp x).

Quantization convention (shared with quantization/weight_only.py):
``amax = max(|x|)`` clamped to 1e-8, codes =
``round(clip(x, -amax, amax) / amax * 127)``, stored scale =
``amax / 127`` so dequant is ``codes * scale``.  Re-quantizing an
untouched block under its own scale is EXACT (codes round-trip), so
the steady-state read-modify-write only loses precision on the
one-time event of a block's amax actually growing.

Scale-pool invariants (the serving/kvcache.py contract, extended):
one scale row ``[H]`` per physical block per layer per K/V; scales
travel WITH their block everywhere a block moves (copy-on-write,
export/import over the migration wire); shared (prefix-cache /
adopted) blocks are never re-quantized — writes only ever land in a
slot's own fresh blocks, so a shared block's scale is immutable while
shared.  Freshly allocated blocks get their scale rows ZEROED
(``codes * 0 = 0`` nullifies any stale garbage) before first write —
see ``Engine._zero_fresh_scales`` for why codes need no zeroing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-8  # amax clamp, matching weight_only's quantizer


class QuantKV:
    """One layer's quantized K (or V) block pool: int8 ``codes``
    ``[NB, bs, H, hd]`` + f32 ``scale`` ``[NB, H]`` (per-block
    per-head dequant multiplier).  Registered as a jax pytree so it
    flows through the engine's existing ``k_pools`` / ``v_pools``
    lists — every compiled dispatch keeps its (donated) pool
    arguments and signatures unchanged.  ``.shape`` / ``.dtype``
    proxy the codes array: callers that only read pool geometry
    (``k_pools[0].shape[1]`` for the block size) work on both forms.
    """

    __slots__ = ("codes", "scale")

    def __init__(self, codes, scale):
        self.codes = codes
        self.scale = scale

    @property
    def shape(self):
        return self.codes.shape

    @property
    def dtype(self):
        return self.codes.dtype

    def __repr__(self):
        return (f"QuantKV(codes={getattr(self.codes, 'shape', None)}, "
                f"scale={getattr(self.scale, 'shape', None)})")


jax.tree_util.register_pytree_node(
    QuantKV,
    lambda p: ((p.codes, p.scale), None),
    lambda _, leaves: QuantKV(*leaves))


def quantize_blocks(vals):
    """Whole-block quantize: f32 ``[n, bs, H, hd]`` -> (int8 codes,
    f32 scale ``[n, H]``) with a FRESH per-block per-head scale.
    Used where whole blocks are produced at once (the monolithic
    paged prefill's tail scatter, tests) — zero pad rows cannot
    inflate the amax, so a padded partial block quantizes its real
    rows at full precision."""
    vals = vals.astype(jnp.float32)
    amax = jnp.maximum(jnp.max(jnp.abs(vals), axis=(1, 3)), _EPS)
    scale = amax / 127.0                                   # [n, H]
    q = jnp.round(jnp.clip(vals, -amax[:, None, :, None],
                           amax[:, None, :, None])
                  / amax[:, None, :, None] * 127.0)
    return q.astype(jnp.int8), scale


def dequantize_blocks(codes, scale):
    """int8 ``[..., bs, H, hd]`` x f32 ``[..., H]`` -> f32 blocks
    (``codes * scale``, broadcast over rows and head_dim)."""
    return codes.astype(jnp.float32) * scale[..., None, :, None]


def paged_gather(pool, block_tables):
    """Dequantized logical rows for a batch of block tables:
    ``pool`` QuantKV, ``block_tables`` int32 ``[B, nbt]`` ->
    f32 ``[B, nbt*bs, H, hd]``.  The dequant multiplies the GATHERED
    blocks only — never the whole pool — and sits adjacent to the
    attention contraction so XLA fuses it into the operand read."""
    c = pool.codes[block_tables]            # [B, nbt, bs, H, hd]
    s = pool.scale[block_tables]            # [B, nbt, H]
    kf = c.astype(jnp.float32) * s[:, :, None, :, None]
    B = block_tables.shape[0]
    return kf.reshape(B, -1, c.shape[3], c.shape[4])


def paged_insert(pool, blk, off, vals):
    """Insert per-lane rows into a quantized block pool — the
    TOUCHED-BLOCK read-modify-write that keeps quantization at block
    granularity under incremental decode writes:

    1. gather each lane's target block (codes + scale), dequantize;
    2. overwrite the written rows.  Lanes sharing one physical block
       (a verify window spanning a block, parked slots on the scratch
       block) are ALL folded into EVERY copy of that block via a
       same-block x one-hot(row) selection, so duplicate copies are
       identical and the scatter-back's last-write-wins is
       deterministic;
    3. recompute the per-block per-head amax scale and requantize the
       WHOLE block.  Untouched rows round-trip exactly under an
       unchanged scale; a grown amax is a one-time precision step for
       the block's older rows.

    ``pool``: QuantKV; ``blk``/``off``: int32 ``[N]`` physical block
    and in-block row per lane; ``vals``: ``[N, H, hd]`` lane rows.
    Returns a new QuantKV.  Masked/parked lanes must be pre-routed to
    the scratch block (blk 0, off 0) by the caller — the same
    one-masking-rule contract as the fp scatter paths."""
    codes, scale = pool.codes, pool.scale
    bs = codes.shape[1]
    vals = vals.astype(jnp.float32)
    kf = dequantize_blocks(codes[blk], scale[blk])   # [N, bs, H, hd]
    # sel[i, j, r]: lane j writes row r of lane i's block copy
    sel = (blk[None, :] == blk[:, None])[:, :, None] \
        & (off[None, :, None] == jnp.arange(bs)[None, None, :])
    written = jnp.any(sel, axis=1)                   # [N, bs]
    ins = jnp.einsum("ijr,jhd->irhd", sel.astype(jnp.float32), vals)
    kf = jnp.where(written[:, :, None, None], ins, kf)
    q, s = quantize_blocks(kf)
    return QuantKV(codes.at[blk].set(q), scale.at[blk].set(s))


def _iter_block_linears(model):
    """Yield ``(path, layer)`` for every plain ``nn.Linear`` inside
    the model's transformer blocks (embeddings / lm_head excluded —
    weight-only serving quantizes the bandwidth-bound block matmuls
    and leaves the tied embedding table alone)."""
    from .. import nn
    from ..quantization.weight_only import WeightOnlyInt8Linear
    for bi, block in enumerate(model.blocks):
        stack = [(f"blocks[{bi}]", block)]
        while stack:
            prefix, layer = stack.pop()
            for name, child in layer.named_children():
                path = f"{prefix}.{name}"
                if isinstance(child, WeightOnlyInt8Linear):
                    continue
                if isinstance(child, nn.Linear):
                    yield path, child
                else:
                    stack.append((path, child))


def relayout_weights_int8(model, compute_dtype=None):
    """Validate, then relayout every transformer-block Linear of a
    serving checkpoint through weight-only int8
    (quantization/weight_only.py math: per-output-channel abs-max
    codes, no calibration).  Validation runs FIRST over the whole
    model and raises a ``ValueError`` NAMING the offending layer —
    the old failure mode surfaced ``WeightOnlyInt8Linear``'s generic
    shape error from deep inside the relayout loop, after earlier
    layers were already swapped, leaving the model half-quantized.
    Returns the number of relayouted layers."""
    todo = list(_iter_block_linears(model))
    for path, lin in todo:
        w = getattr(lin, "weight", None)
        data = getattr(w, "_data", None)
        if data is None or data.ndim != 2 \
                or not jnp.issubdtype(data.dtype, jnp.floating):
            got = (f"shape {list(data.shape)} dtype {data.dtype}"
                   if data is not None else "no weight")
            raise ValueError(
                f"weight_dtype='int8' cannot relayout layer {path}: "
                f"{got} — weight-only int8 codes need a 2-D floating "
                "[in, out] Linear weight (conv/other kernels need "
                "quantization.int8's calibrated forms)")
    if not todo:
        raise ValueError(
            "weight_dtype='int8' found no Linear layers in "
            "model.blocks to relayout — the tensor-parallel einsum "
            "form (use_mp=True) and pre-quantized models have "
            "nothing to code")
    from ..quantization.weight_only import quantize_weights_int8
    for block in model.blocks:
        quantize_weights_int8(block, compute_dtype=compute_dtype)
    return len(todo)
