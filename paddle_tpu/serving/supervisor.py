"""Self-healing fleet supervisor: the tier that brings replicas BACK.

The router tier (``serving.router``) detects a dead replica and routes
around it; nothing in the stack ever restarted one, so every kill
permanently shrank capacity.  ``FleetSupervisor`` closes the loop: it
owns a set of replica process handles (a ``distributed.launch``
``ServingFleet`` in production, any duck-typed fake in tests) and
keeps the fleet at target size:

* **death by exit** — ``handle.alive()`` false (the process
  terminated, e.g. a ``proc_kill9`` chaos firing or an OOM kill);
* **death by wedge** — the process is alive but ``/livez`` probes
  time out ``wedge_after`` times in a row, or a probe answers with
  ``watchdog_fired`` (the engine's tick watchdog declared a wedged
  dispatch).  A SIGSTOP'd process (``proc_stop``) is the canonical
  wedge: ``poll()`` says alive, the socket never answers.  The
  supervisor SIGKILLs the wedged process — SIGKILL terminates even
  stopped processes — and treats it as a death;
* **restart with exponential backoff + seeded jitter** — the k-th
  restart inside the crash-loop window waits
  ``min(cap, base * 2^k)`` scaled by a deterministic jitter drawn
  from ``blake2b(seed:replica:incarnation)`` (the fault injector's
  pure-hash idiom), so a storm replay restarts on the same schedule;
* **crash-loop quarantine** — ``crashloop_threshold`` restarts inside
  ``crashloop_window_s`` trips a supervisor-level breaker: the
  replica is QUARANTINED (no further restarts burn capacity on a
  replica that exits on boot) until an operator ``release()``\\ s it;
* **incarnation ids** — every restart stamps the successor process
  with ``incarnation + 1`` (httpd's ``--incarnation`` flag, surfaced
  on ``/healthz``).  The router registry keys its circuit breaker and
  health history on the incarnation: a probe from a dead incarnation
  can never poison its successor, and a successor never inherits the
  predecessor's half-open breaker state.

The supervisor NEVER consults the fault schedule — chaos is the storm
driver's job (``faults.PROC_SITES``); the supervisor only observes
and heals, so supervised and unsupervised runs of the same seed see
the identical fault sequence and the ``restart_log`` is a pure
consequence of it (same seed => same death/restart/quarantine log,
asserted by the kill-storm tests).

Metrics (in the supplied registry): ``supervisor.restarts_total``,
``supervisor.deaths_total``, ``supervisor.quarantined`` (gauge).
Spans: ``supervisor.restart`` around each respawn (broken out by
``tools/trace_view.py --wall``), instants for death / wedge /
quarantine / release.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.request

from .. import monitor

UP = "up"
BACKOFF = "backoff"
QUARANTINED = "quarantined"


def _u01(seed, *parts):
    """Deterministic uniform in [0, 1) from a blake2b hash — the
    FaultInjector's pure-schedule idiom, reused for restart jitter so
    a replayed storm restarts on the identical schedule."""
    key = ":".join([str(seed)] + [str(p) for p in parts])
    h = hashlib.blake2b(key.encode(), digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


class SupervisorPolicy:
    """FleetSupervisor tuning knobs (defaults are production-shaped;
    tests shrink the time constants).

    poll_interval_s : background sweep period.
    livez_timeout_s : per-probe timeout; an unanswered probe counts
        toward the wedge verdict.
    wedge_after : consecutive failed/watchdog probes before a live
        process is declared wedged and killed.
    boot_grace_s : after a (re)spawn, probe failures are forgiven for
        this long (a replica importing its ML stack answers nothing
        for many seconds; killing it for that would be a crash loop
        of the supervisor's own making).  Process EXIT still counts
        immediately.
    backoff_base_s / backoff_cap_s / backoff_jitter : restart delay
        ``min(cap, base * 2^k)`` for the k-th restart in the window,
        scaled by ``1 + jitter * (2u - 1)`` with the seeded draw u.
    crashloop_window_s / crashloop_threshold : this many restarts
        inside the window quarantines the replica.
    wedge_on_watchdog : count a probe that answers with
        ``watchdog_fired`` as a wedge strike (the engine itself says
        its tick is stuck); off, only unanswered probes count.
    seed : determinism root for the jitter draws.
    """

    def __init__(self, poll_interval_s=0.5, livez_timeout_s=1.0,
                 wedge_after=3, boot_grace_s=120.0,
                 backoff_base_s=0.25, backoff_cap_s=10.0,
                 backoff_jitter=0.5, crashloop_window_s=60.0,
                 crashloop_threshold=3, wedge_on_watchdog=True,
                 seed=0):
        if wedge_after < 1:
            raise ValueError(
                f"wedge_after must be >= 1, got {wedge_after}")
        if crashloop_threshold < 1:
            raise ValueError(f"crashloop_threshold must be >= 1, got "
                             f"{crashloop_threshold}")
        if backoff_base_s < 0 or backoff_cap_s < 0:
            raise ValueError("backoff must be >= 0, got "
                             f"{backoff_base_s}/{backoff_cap_s}")
        if not 0 <= backoff_jitter <= 1:
            raise ValueError(f"backoff_jitter must be in [0, 1], got "
                             f"{backoff_jitter}")
        self.poll_interval_s = float(poll_interval_s)
        self.livez_timeout_s = float(livez_timeout_s)
        self.wedge_after = int(wedge_after)
        self.boot_grace_s = float(boot_grace_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.backoff_jitter = float(backoff_jitter)
        self.crashloop_window_s = float(crashloop_window_s)
        self.crashloop_threshold = int(crashloop_threshold)
        self.wedge_on_watchdog = bool(wedge_on_watchdog)
        self.seed = int(seed)


class ProcessReplica:
    """Supervisor handle over one ``ServingFleet`` slot — the real-
    process driver.  The handle contract (duck-typed; tests fake it):

    * ``alive() -> bool`` — the process exists and has not exited;
    * ``exit_code()`` — returncode once dead (None while alive);
    * ``kill()`` — SIGKILL + reap (works on SIGSTOP-wedged children);
    * ``spawn(incarnation)`` — (re)start the process advertising that
      incarnation; must not block on readiness (the supervisor's
      ``boot_grace_s`` owns that wait);
    * ``probe_live(timeout_s) -> dict`` — liveness probe; raises when
      the process does not answer within the timeout.  The returned
      dict MAY carry ``watchdog_fired``.

    ``probe_live`` fetches ``/healthz`` (one round trip covers both
    wedge conditions: an unanswered fetch IS the ``/livez`` timeout —
    the same HTTP thread serves both paths — and the body carries the
    engine's ``watchdog_fired`` flag)."""

    def __init__(self, fleet, index, name=None):
        self.fleet = fleet
        self.index = int(index)
        self.name = (str(name) if name is not None
                     else f"replica{int(index)}")
        self.url = fleet.urls[self.index]

    def alive(self):
        return self.fleet.procs[self.index].poll() is None

    def exit_code(self):
        return self.fleet.procs[self.index].poll()

    def kill(self):
        self.fleet.kill(self.index)

    def spawn(self, incarnation):
        self.fleet.respawn(self.index, incarnation=int(incarnation))

    def probe_live(self, timeout_s):
        with urllib.request.urlopen(self.url + "/healthz",
                                    timeout=float(timeout_s)) as r:
            return json.loads(r.read())


class _SupState:
    """Per-replica supervision record."""

    def __init__(self, handle, incarnation=0):
        self.handle = handle
        self.incarnation = int(incarnation)
        self.state = UP
        self.restart_at = None    # monotonic deadline while BACKOFF
        self.recent = []          # restart stamps in the window
        self.live_fails = 0       # consecutive wedge strikes
        self.boot_until = None    # probe-forgiveness deadline
        self.confirmed = False    # answered a probe since (re)spawn


class FleetSupervisor:
    """Keep a replica fleet at target size (module docstring has the
    full story).  ``replicas``: dict name -> handle, or an iterable
    of handles with ``.name``.  Deterministic tests drive
    ``poll_once(now=...)`` directly; production runs ``start()``'s
    daemon sweep thread."""

    def __init__(self, replicas, policy=None, registry=None,
                 tracing=True, trace_capacity=8192):
        self.policy = policy or SupervisorPolicy()
        self.registry = registry or monitor.default_registry()
        self.tracer = (monitor.Tracer(capacity=trace_capacity)
                       if tracing else monitor.NullTracer())
        if isinstance(replicas, dict):
            items = list(replicas.items())
        else:
            items = [(getattr(h, "name"), h) for h in replicas]
        self._states = {str(n): _SupState(h) for n, h in items}
        if len(self._states) != len(items):
            raise ValueError("replica names must be unique")
        self.restart_log = []   # ("death"|"restart"|"quarantine"|
        #   "release", name, incarnation[, reason]) — wall-clock free,
        #   so the same seed + fault schedule replays the same log
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        reg = self.registry
        self._m_restarts = reg.counter(
            "supervisor.restarts_total",
            "replica processes restarted by the supervisor")
        self._m_deaths = reg.counter(
            "supervisor.deaths_total",
            "replica deaths observed (process exit + wedge kills)")
        self._m_quarantined = reg.gauge(
            "supervisor.quarantined",
            "replicas currently quarantined by the crash-loop breaker")

    # -- views ---------------------------------------------------------
    def target_size(self):
        return len(self._states)

    def quarantined(self):
        """Names currently behind the crash-loop breaker."""
        return sorted(n for n, s in self._states.items()
                      if s.state == QUARANTINED)

    def incarnation(self, name):
        return self._states[str(name)].incarnation

    def status(self):
        """JSON-shaped fleet view (the bench / examples surface)."""
        rows = {}
        for n, s in sorted(self._states.items()):
            rows[n] = {
                "state": s.state,
                "incarnation": s.incarnation,
                "alive": bool(s.handle.alive()),
                "confirmed": s.confirmed,
                "recent_restarts": len(s.recent),
                "live_fails": s.live_fails,
            }
        return {"target": self.target_size(), "replicas": rows,
                "quarantined": self.quarantined()}

    def chrome_trace(self):
        return self.tracer.chrome_trace(process_name="supervisor")

    # -- the sweep -----------------------------------------------------
    def poll_once(self, now=None):
        """One supervision sweep over every replica, in name order
        (deterministic).  Returns {name: state} after the sweep."""
        now = time.monotonic() if now is None else float(now)
        p = self.policy
        out = {}
        for name in sorted(self._states):
            s = self._states[name]
            if s.state == QUARANTINED:
                out[name] = s.state
                continue
            if s.state == BACKOFF:
                if now >= s.restart_at:
                    self._restart(name, s, now)
                out[name] = s.state
                continue
            # state == UP
            if not s.handle.alive():
                self._on_death(
                    name, s, f"exit:{s.handle.exit_code()}", now)
                out[name] = s.state
                continue
            wedged = False
            info = None
            try:
                info = s.handle.probe_live(p.livez_timeout_s)
            except Exception:
                wedged = True
            if info is not None and p.wedge_on_watchdog \
                    and info.get("watchdog_fired"):
                wedged = True
            in_boot = s.boot_until is not None and now < s.boot_until
            if wedged and not in_boot:
                s.live_fails += 1
            elif not wedged:
                s.live_fails = 0
                s.boot_until = None   # first clean probe ends boot
                s.confirmed = True
            if s.live_fails >= p.wedge_after:
                # alive-but-unresponsive: SIGKILL (terminates even a
                # SIGSTOP'd process) and walk the normal death path
                self.tracer.instant("supervisor.wedge",
                                    cat="supervisor", replica=name,
                                    incarnation=s.incarnation)
                try:
                    s.handle.kill()
                except Exception:
                    pass
                self._on_death(name, s, "wedge", now)
            out[name] = s.state
        return out

    def _on_death(self, name, s, reason, now):
        self._m_deaths.inc()
        self.restart_log.append(
            ("death", name, s.incarnation, reason))
        self.tracer.instant("supervisor.death", cat="supervisor",
                            replica=name, incarnation=s.incarnation,
                            reason=reason)
        p = self.policy
        s.live_fails = 0
        s.boot_until = None
        s.confirmed = False
        s.recent = [t for t in s.recent
                    if now - t <= p.crashloop_window_s]
        if len(s.recent) >= p.crashloop_threshold:
            s.state = QUARANTINED
            self.restart_log.append(
                ("quarantine", name, s.incarnation))
            self.tracer.instant("supervisor.quarantine",
                                cat="supervisor", replica=name,
                                incarnation=s.incarnation)
            self._m_quarantined.set(len(self.quarantined()))
            return
        k = len(s.recent)
        delay = min(p.backoff_cap_s, p.backoff_base_s * (2 ** k))
        u = _u01(p.seed, "restart", name, s.incarnation + 1)
        delay *= 1.0 + p.backoff_jitter * (2.0 * u - 1.0)
        s.restart_at = now + delay
        s.state = BACKOFF

    def _restart(self, name, s, now):
        s.incarnation += 1
        with self.tracer.span("supervisor.restart", cat="supervisor",
                              replica=name,
                              incarnation=s.incarnation):
            try:
                s.handle.spawn(s.incarnation)
            except Exception:
                # the spawn itself failed (exec error, port bind):
                # treat like an instant death — backoff grows and the
                # crash-loop breaker eventually quarantines
                s.recent.append(now)
                self._on_death(name, s, "spawn_failed", now)
                return
        s.recent.append(now)
        s.state = UP
        s.live_fails = 0
        s.boot_until = now + self.policy.boot_grace_s
        self.restart_log.append(("restart", name, s.incarnation))
        self._m_restarts.inc()

    def release(self, name):
        """Operator override: lift a quarantine.  The crash-loop
        window resets and the replica restarts on the next sweep."""
        s = self._states[str(name)]
        if s.state != QUARANTINED:
            raise ValueError(f"replica {name!r} is not quarantined "
                             f"(state={s.state})")
        s.recent = []
        s.live_fails = 0
        s.restart_at = -float("inf")   # due immediately
        s.state = BACKOFF
        self.restart_log.append(("release", str(name), s.incarnation))
        self.tracer.instant("supervisor.release", cat="supervisor",
                            replica=str(name),
                            incarnation=s.incarnation)
        self._m_quarantined.set(len(self.quarantined()))

    # -- waiting helpers ----------------------------------------------
    def wait_fleet_up(self, timeout_s=60.0, poll_s=None):
        """Sweep until every non-quarantined replica is UP, alive AND
        probe-confirmed (the storm tests' convergence wait).  The
        confirmation requirement matters for crash-loopers: an armed
        exit-on-boot child is briefly alive after every respawn, so
        "alive" alone flickers true mid-loop — a replica only counts
        once it has answered a live probe since its last (re)spawn,
        which a crash-looper never does.  Returns True on success,
        False on timeout."""
        poll_s = (self.policy.poll_interval_s if poll_s is None
                  else float(poll_s))
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            states = self.poll_once()
            if all(st == QUARANTINED
                   or (st == UP and self._states[n].confirmed
                       and self._states[n].handle.alive())
                   for n, st in states.items()):
                return True
            time.sleep(poll_s)
        return False

    # -- background sweep ----------------------------------------------
    def start(self):
        """Run the sweep on a daemon thread."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop = threading.Event()
        stop = self._stop

        def loop():
            while not stop.wait(self.policy.poll_interval_s):
                try:
                    self.poll_once()
                except Exception:
                    pass  # the supervisor must outlive one bad sweep

        self._thread = threading.Thread(
            target=loop, daemon=True,
            name="paddle_tpu-serving-supervisor")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def supervise_fleet(fleet, policy=None, registry=None, names=None):
    """FleetSupervisor over a spawned ``ServingFleet``: one
    ``ProcessReplica`` handle per slot (respawn-on-same-URL via
    ``ServingFleet.respawn``).  ``names`` optionally labels the
    slots; default ``replica0..N-1``."""
    handles = [ProcessReplica(
        fleet, i, name=(names[i] if names else None))
        for i in range(len(fleet.procs))]
    return FleetSupervisor(handles, policy=policy, registry=registry)
