"""Slot scheduler: maps queued requests onto fixed batch slots.

Continuous batching over a FIXED pool (the TPU-shaped version: slot
count and cache length are compile-time constants, so one XLA program
serves every tick — Ragged Paged Attention, PAPERS.md 2604.15464, is
the kernel-level generalization of the same idea).  The scheduler owns
only slot METADATA; the engine owns the device arrays.  Admission =
bind request to a free slot (the engine then prefills it); eviction =
free the slot on EOS / max_new_tokens / error.

Slot lifecycle (budgeted chunked prefill, serving/engine.py
``prefill_chunk``): a bound slot whose ``prefilled`` has not reached
its prompt length is PREFILLING — it holds cache rows but is excluded
from the decode set (``snapshot().decoding``) and from sampling until
its final chunk emits the first token.  Monolithic prefill jumps
``prefilled`` straight to the prompt length at admission, so the
DECODING condition is uniform across both modes.
"""
from __future__ import annotations

import threading


class Slot:
    __slots__ = ("index", "request", "pos", "prefilled", "seq",
                 "spec_lanes")

    def __init__(self, index):
        self.index = index
        self.request = None
        self.pos = 0        # next cache write position (= tokens cached)
        self.prefilled = 0  # prompt tokens whose K/V is computed; a
        #                     bound slot with prefilled < len(prompt) is
        #                     PREFILLING (chunked mode), else DECODING
        self.seq = 0        # admission order stamp: chunked prefill
        #                     resumes earlier-admitted (partially done)
        #                     prompts before starting fresh ones
        self.spec_lanes = 0  # REAL draft lanes in flight in the
        #                      current speculative verify dispatch
        #                      (the engine's accept loop consumes at
        #                      most this many — pad lanes never
        #                      match); reset on admit/evict, so a slot
        #                      that failed mid-verify re-binds clean —
        #                      the rejected lanes' K/V needs no other
        #                      cleanup (cursor never advanced over
        #                      them)

    @property
    def free(self):
        return self.request is None

    @property
    def decoding(self):
        """Bound AND fully prefilled — eligible for the decode tick.
        Measured against ``req.context`` (prompt, or the frozen
        prompt+emitted resume snapshot after a preemption): a resumed
        request is only DECODING once its whole interrupted history
        has K/V again."""
        req = self.request
        return req is not None and self.prefilled >= len(req.context)


class Scheduler:
    """Admits queued requests into free slots; evicts finished ones."""

    def __init__(self, num_slots, queue):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = int(num_slots)
        self.queue = queue
        self.slots = [Slot(i) for i in range(self.num_slots)]
        self._lock = threading.Lock()
        self._admit_seq = 0

    # -- accounting ------------------------------------------------------
    def occupancy(self):
        with self._lock:
            return sum(1 for s in self.slots if not s.free)

    def free_count(self):
        # one acquisition, not occupancy() through a second one
        with self._lock:
            return sum(1 for s in self.slots if s.free)

    def active_slots(self):
        """Decode-eligible slots (bound and fully prefilled) —
        half-prefilled chunked slots are excluded until their final
        chunk emits the first token."""
        with self._lock:
            return [s for s in self.slots if s.decoding]

    def busy_slots(self):
        """Every bound slot, PREFILLING included — the eviction set for
        failure recovery and shutdown drain (a half-prefilled request's
        waiter must unblock too)."""
        with self._lock:
            return [s for s in self.slots if not s.free]

    def debug_view(self):
        """ONE locked pass over the pool for the debug surface
        (``/debug/requests``, flight recorder): per-slot metadata as
        plain dicts, request handle included — the engine enriches
        and serializes.  Read-only; safe from any thread."""
        with self._lock:
            out = []
            for s in self.slots:
                req = s.request
                state = ("free" if req is None else
                         "decoding" if s.prefilled >= len(req.context)
                         else "prefilling")
                out.append({"slot": s.index, "state": state,
                            "request": req, "pos": s.pos,
                            "prefilled": s.prefilled,
                            "spec_lanes": s.spec_lanes,
                            "priority": (None if req is None
                                         else req.priority),
                            "tenant": (None if req is None
                                       else req.tenant)})
        return out

    def snapshot(self):
        """ONE locked pass over the pool: (occupancy, decoding slots,
        prefilling slots ordered by admission).  The engine's per-tick
        view — replaces the separate ``occupancy()`` /
        ``active_slots()`` acquisitions the tick used to pay."""
        with self._lock:
            busy = [s for s in self.slots if not s.free]
            decoding = [s for s in busy if s.decoding]
            prefilling = sorted((s for s in busy if not s.decoding),
                                key=lambda s: s.seq)
        return len(busy), decoding, prefilling

    def find(self, request_id):
        """The slot a request is bound to, or None (queued / unknown /
        finished).  One locked scan — the migration service point
        re-resolves its target after every ring drain, since a drain
        can finish or evict any slot."""
        with self._lock:
            for s in self.slots:
                if s.request is not None and s.request.id == request_id:
                    return s
        return None

    def idle(self):
        return self.occupancy() == 0 and self.queue.depth() == 0

    def admissible(self):
        """True when an admission attempt could make progress: at
        least one queued request AND at least one free slot.  The
        async engine tick's cheap planning probe — admission is a
        structural (pipeline-draining) event, so the pipelined loop
        only pays ``admit()`` when this says it could bind."""
        if self.queue.depth() == 0:
            return False
        with self._lock:
            return any(s.free for s in self.slots)

    # -- admission / eviction -------------------------------------------
    def admit(self, now=None, gate=None):
        """Fill free slots from the queue.  Returns (admitted_slots,
        timed_out_requests) — the engine prefills each admitted slot
        and counts the timeouts.

        ``gate``: optional resource check consulted per request BEFORE
        the slot binds, called as ``gate(req, slot)`` with the slot
        the request WOULD bind to (the engine's paged-KV admission
        gate: prefix cache lookup + up-front block reservation — under
        a data-parallel mesh the reservation must come from the
        binding slot's own dp shard, hence the slot).  A False verdict
        puts the request back at the queue head and stops this round's
        admission — FIFO order is preserved and later ticks retry once
        eviction/completion frees resources.

        Locking: two acquisitions per call (free-slot scan + one batch
        bind), however many slots admit — admission runs only on the
        engine loop thread, so deferring the binds cannot race another
        writer; concurrent readers (``/healthz``) just see the slots
        bind a moment later."""
        timed_out, binds = [], []
        with self._lock:
            free = [s for s in self.slots if s.free]
        for slot in free:
            req, expired = self.queue.pop_ready(now)
            timed_out.extend(expired)
            if req is None:
                break
            if gate is not None:
                try:
                    admit_ok = gate(req, slot)
                except BaseException:
                    # a gate that RAISES (e.g. pool failure mid-
                    # reservation) must not lose popped requests: put
                    # this one and every not-yet-bound earlier pop
                    # back in order, so their waiters survive the
                    # step-failure recovery and later ticks retry
                    # (stale _kv_plan reservations are overwritten by
                    # the re-admission gate after the pool rebuilds)
                    self.queue.push_front(req)
                    for _, r in reversed(binds):
                        self.queue.push_front(r)
                    raise
                if not admit_ok:
                    self.queue.push_front(req)
                    break
            binds.append((slot, req))
        if binds:
            with self._lock:
                for slot, req in binds:
                    slot.request = req
                    slot.pos = 0
                    slot.prefilled = 0
                    slot.spec_lanes = 0
                    self._admit_seq += 1
                    slot.seq = self._admit_seq
        return [s for s, _ in binds], timed_out

    def release(self, slot):
        """Unbind a slot WITHOUT completing its request — the
        PREEMPTION path: the caller (engine) requeues the request with
        its emitted tokens preserved, so its waiter stays blocked and
        the stream resumes on re-admission.  Returns the request."""
        with self._lock:
            req = slot.request
            slot.request = None
            slot.pos = 0
            slot.prefilled = 0
            slot.spec_lanes = 0
        return req

    def evict(self, slot, error=None):
        """Free a slot and complete its request."""
        with self._lock:
            req = slot.request
            slot.request = None
            slot.pos = 0
            slot.prefilled = 0
            slot.spec_lanes = 0
        if req is not None:
            req._finish(error)
        return req
