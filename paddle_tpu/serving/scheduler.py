"""Slot scheduler: maps queued requests onto fixed batch slots.

Continuous batching over a FIXED pool (the TPU-shaped version: slot
count and cache length are compile-time constants, so one XLA program
serves every tick — Ragged Paged Attention, PAPERS.md 2604.15464, is
the kernel-level generalization of the same idea).  The scheduler owns
only slot METADATA; the engine owns the device arrays.  Admission =
bind request to a free slot (the engine then prefills it); eviction =
free the slot on EOS / max_new_tokens / error.
"""
from __future__ import annotations

import threading


class Slot:
    __slots__ = ("index", "request", "pos")

    def __init__(self, index):
        self.index = index
        self.request = None
        self.pos = 0   # next cache write position (= tokens cached)

    @property
    def free(self):
        return self.request is None


class Scheduler:
    """Admits queued requests into free slots; evicts finished ones."""

    def __init__(self, num_slots, queue):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = int(num_slots)
        self.queue = queue
        self.slots = [Slot(i) for i in range(self.num_slots)]
        self._lock = threading.Lock()

    # -- accounting ------------------------------------------------------
    def occupancy(self):
        with self._lock:
            return sum(1 for s in self.slots if not s.free)

    def free_count(self):
        return self.num_slots - self.occupancy()

    def active_slots(self):
        with self._lock:
            return [s for s in self.slots if not s.free]

    def idle(self):
        return self.occupancy() == 0 and self.queue.depth() == 0

    # -- admission / eviction -------------------------------------------
    def admit(self, now=None, gate=None):
        """Fill free slots from the queue.  Returns (admitted_slots,
        timed_out_requests) — the engine prefills each admitted slot
        and counts the timeouts.

        ``gate``: optional resource check consulted per request BEFORE
        the slot binds (the engine's paged-KV admission gate: prefix
        cache lookup + up-front block reservation).  A False verdict
        puts the request back at the queue head and stops this round's
        admission — FIFO order is preserved and later ticks retry once
        eviction/completion frees resources."""
        admitted, timed_out = [], []
        with self._lock:
            free = [s for s in self.slots if s.free]
        for slot in free:
            req, expired = self.queue.pop_ready(now)
            timed_out.extend(expired)
            if req is None:
                break
            if gate is not None and not gate(req):
                self.queue.push_front(req)
                break
            with self._lock:
                slot.request = req
                slot.pos = 0
            admitted.append(slot)
        return admitted, timed_out

    def evict(self, slot, error=None):
        """Free a slot and complete its request."""
        with self._lock:
            req = slot.request
            slot.request = None
            slot.pos = 0
        if req is not None:
            req._finish(error)
        return req
