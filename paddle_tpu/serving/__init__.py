"""paddle_tpu.serving — continuous-batching inference engine.

The serving workload class (ROADMAP: "serve heavy traffic from millions
of users"): an in-process ``Engine`` runs ONE jitted one-token decode
step over a fixed pool of batch slots, a ``Scheduler`` admits queued
requests into free slots (prefill on admission, eviction on EOS /
max_new_tokens), a ``RequestQueue`` enforces per-request deadlines, and
``serving.httpd`` exposes the whole thing over stdlib HTTP for smoke
serving.  ``serving.kvcache`` pages the K/V pools into fixed-size
refcounted blocks (``Engine(kv_block_size=...)``): identical prompt
prefixes share physical blocks and a token-trie ``PrefixCache`` lets
admission skip prefill for previously-seen spans, with LRU eviction
under pool pressure.  ``Engine(prefill_chunk=...,
tick_token_budget=...)`` adds budgeted CHUNKED prefill: prompts split
into fixed-size chunks interleaved with decode so a long prompt can
no longer stall token emission for the active slots (decode latency
is bounded by the per-tick token budget, not the longest queued
prompt).  ``Engine(spec_k=..., proposer=...)`` turns the decode tick
into SPECULATIVE draft-and-verify (``serving.spec``): a proposer —
``PromptLookupProposer`` (n-gram match on the slot's own history, no
extra model) or ``DraftModelProposer`` (a smaller GPT) — guesses k
tokens per slot, ONE jitted verify dispatch scores all k+1 positions,
and the engine keeps the longest argmax-matching prefix plus the
bonus token: 1..k+1 tokens per dispatch, greedy outputs still
token-identical to the non-speculative engine.
``Engine(sample_mode="device")`` (the default) FUSES sampling into
the jitted dispatches: per-slot temperature/top_k/top_p as traced
lanes, rng keys derived on device from the request seed +
emitted-token counter, device-resident step cursors — a steady-state
tick uploads nothing and downloads only the sampled ids (+ accept
counts under speculation) instead of the per-tick logits matrix;
``sample_mode="host"`` keeps the legacy logits-download + numpy
sampling numerics.  ``Engine(weight_dtype="int8")`` /
``Engine(kv_dtype="int8")`` add QUANTIZED serving (``serving.quant``):
weight-only int8 codes ride the compiled hot paths as traced buffers,
and the paged K/V pools store int8 codes with per-block per-head f32
scales (``QuantKV``) so the same ``kv_budget_mb`` holds ~2x the
logical blocks vs bf16 (~4x vs f32) — quantized blocks stay
first-class through prefix sharing, preemption, recovery, and the
migration wire (a ``kv_dtype``-mismatched peer raises
``KVDtypeMismatch`` instead of adopting garbage).  Metrics (queue depth, slot occupancy, tokens/sec,
TTFT/TPOT, KV blocks in use, prefix hits/evictions, prefill chunks,
decode stall, spec proposed/accepted/acceptance-rate/tokens-per-tick,
d2h bytes per tick, host sample time, fused-sample ticks, compiles)
land in paddle_tpu.monitor and render via ``render_prometheus()``.
Every engine also runs a tick-level span tracer (monitor/tracing.py:
bounded per-thread rings, phase spans + request lifecycle instants +
compile events) with chrome-trace export (``Engine.chrome_trace()``,
``GET /debug/trace``), a live request view (``GET /debug/requests``),
and an automatic flight-recorder dump on step failure
(``Engine(flight_dir=...)``).  OVERLOAD PROTECTION:
``submit(priority=..., tenant=...)`` gives requests priority classes
(higher preempts lower MID-STREAM under slot/KV pressure — the
victim's blocks return to the prefix cache and its stream resumes
token-identically on re-admission) and per-tenant weighted-fair
queue service with token-bucket rate limits
(``Engine(tenants={...})``); deadline-aware shedding rejects
requests whose deadline the measured drain rate already cannot meet
(``DeadlineShed`` with an honest computed Retry-After);
``stop(drain=True)`` drains gracefully (in-flight streams finish,
bounded by a timeout); and ``serving.faults`` provides the
deterministic chaos harness (seeded fault schedule over
dispatch/d2h/pool/host sites + a tick watchdog that converts wedged
dispatches into flight-recorded recoveries).
``Engine(adapters={name: LoRAAdapter(...)})`` adds MULTI-ADAPTER
serving (``serving.lora``): every adapter's low-rank factors live in
two fixed-shape device banks gathered by a per-slot ``adapter_id``
INSIDE the compiled hot paths — one program serves every adapter,
hot-load/unload is pure data movement (zero recompiles), and
in-flight requests pin their adapter against unload.
``serving.stream`` adds live TOKEN STREAMING: a ``TokenStream``
attaches to a request with exactly-once replay-then-subscribe
semantics, httpd/routerd answer ``{"stream": true}`` as SSE, and the
router's ``generate(on_token=...)`` splices failover/migration
continuations into one seamless stream.
``Engine(kv_host_mb=...)`` adds the HIERARCHICAL KV OFFLOAD tier
(``serving.offload``): blocks the prefix trie evicts under pool
pressure demote into a content-addressed host-RAM ``HostBlockStore``
(async device gathers materialized at tick boundaries, LRU within a
byte budget) instead of vanishing, and admission consults the store
after the device trie — a host hit restores the payload into fresh
device blocks and skips prefill for the span exactly like a device
prefix hit, token-identical to a never-evicted run; int8 KV payloads
carry codes+scales, and the router's prefix warming ships a peer's
host tier before recomputing.
"""
from .request import (  # noqa: F401
    Request, RequestQueue, RequestTimeout, QueueFull, Rejected,
    RateLimited, DeadlineShed, TenantPolicy, TokenBucket)
from .scheduler import Scheduler, Slot  # noqa: F401
from .kvcache import (  # noqa: F401
    BlockPool, KVDtypeMismatch, NoFreeBlocks, PrefixCache)
from .quant import QuantKV, relayout_weights_int8  # noqa: F401
from .spec import (  # noqa: F401
    Proposer, PromptLookupProposer, DraftModelProposer)
from .faults import (  # noqa: F401
    FaultInjector, InjectedFault, NetDisconnect, NetFault, NetRefused,
    NetTimeout, TickWatchdog, WatchdogTimeout)
from .lora import (  # noqa: F401
    AdapterInUse, AdapterRegistry, LoRAAdapter, RegistryFull,
    UnknownAdapter)
from .stream import (  # noqa: F401
    StreamClosed, StreamEvent, TokenStream, parse_sse, sse_format)
from .offload import HostBlockStore, prefix_key  # noqa: F401
from .engine import Engine  # noqa: F401
from .httpd import EngineServer, serve  # noqa: F401
from .router import (  # noqa: F401
    CircuitBreaker, HttpReplicaClient, InProcessReplica,
    NoReplicasAvailable, Replica, ReplicaAbandoned, ReplicaHTTPError,
    ReplicaUnavailable, RequestFailed, Router, RouterError,
    RouterPolicy, UnknownModel, affinity_key)
from .routerd import RouterServer  # noqa: F401
from .supervisor import (  # noqa: F401
    FleetSupervisor, ProcessReplica, SupervisorPolicy,
    supervise_fleet)

__all__ = [
    "Request", "RequestQueue", "RequestTimeout", "QueueFull",
    "Rejected", "RateLimited", "DeadlineShed", "TenantPolicy",
    "TokenBucket",
    "Scheduler", "Slot", "Engine", "EngineServer", "serve",
    "BlockPool", "PrefixCache", "NoFreeBlocks",
    "KVDtypeMismatch", "QuantKV", "relayout_weights_int8",
    "HostBlockStore", "prefix_key",
    "Proposer", "PromptLookupProposer", "DraftModelProposer",
    "FaultInjector", "InjectedFault", "TickWatchdog",
    "WatchdogTimeout",
    "NetFault", "NetRefused", "NetTimeout", "NetDisconnect",
    "LoRAAdapter", "AdapterRegistry", "AdapterInUse", "RegistryFull",
    "UnknownAdapter",
    "TokenStream", "StreamEvent", "StreamClosed", "sse_format",
    "parse_sse",
    "Router", "RouterPolicy", "RouterServer", "RouterError",
    "UnknownModel",
    "NoReplicasAvailable", "RequestFailed", "Replica",
    "ReplicaAbandoned", "ReplicaHTTPError", "ReplicaUnavailable",
    "CircuitBreaker", "HttpReplicaClient", "InProcessReplica",
    "affinity_key",
    "FleetSupervisor", "SupervisorPolicy", "ProcessReplica",
    "supervise_fleet",
]
