"""Resilient multi-replica router: the scale-OUT half of serving.

One ``Engine`` scales up (paged KV, chunked prefill, speculation,
async ticks); this module scales out: a ``Router`` owns a REGISTRY of
engine replicas, probes each one's health on a jittered interval, and
spreads traffic over the live ones.  At fleet scale a replica being
slow, draining, or dead is the steady state, not the exception — so
robustness is the design center, not an afterthought:

* **Prefix-affinity routing.**  The first ``kv_block_size``-aligned
  span of the prompt is hashed (``affinity_key``) and mapped to a
  replica by highest-random-weight (rendezvous) hashing — shared
  system prompts land on the replica whose prefix cache already holds
  their blocks, and replica churn only remaps the keys that touched
  the changed replica.  When the affinity target is sick, breaker-
  open, or its probed ``queue_depth`` crosses a threshold, the pick
  falls back to LEAST-LOADED over the healthy set.

* **Health-probed registry.**  ``probe_once()`` (or the background
  prober ``start()`` runs) hits every replica's ``/healthz``-shaped
  probe and classifies it ``healthy`` / ``degraded`` / ``draining`` /
  ``dead``: one failed probe degrades, ``dead_after`` consecutive
  failures kill, a replica reporting ``draining`` stops receiving new
  requests (mirroring ``Engine.stop(drain=True)`` — it is finishing,
  not dying), and a ``watchdog_fired`` replica is degraded until its
  next clean probe.  Probes also double as the circuit breaker's
  half-open trial: a clean probe against an OPEN breaker re-admits
  real traffic through half-open.

* **Retry / hedge / circuit-break.**  Submit-side failures are
  CLASSIFIED: connection refused, black-hole timeouts (idempotent
  requests only — a lost response may mean executed work), 5xx, and
  probe-declared-dead replicas retry with exponential backoff + seeded
  jitter, honoring a 503's computed ``Retry-After``; 4xx never retry.
  Each replica carries a ``CircuitBreaker`` (consecutive-failure trip
  -> OPEN; after a cooldown, HALF_OPEN admits one trial request whose
  outcome closes or re-opens it).  Optional tail-latency HEDGING for
  idempotent requests dispatches a second copy to the next-best
  replica after a p99-derived delay; the first winner cancels the
  loser.

* **Failover with context.**  A mid-body disconnect carries the
  tokens already received: a GREEDY request resumes on another replica
  with ``prompt + emitted`` as its context (the resumed stream is
  token-identical to the uninterrupted one); sampled requests restart
  from scratch (a seeded stream re-drawn from token 0 is identical —
  resuming mid-stream would shift the device sampling counter).  A
  request still QUEUED on a replica the prober declares dead is
  abandoned and re-routed without losing anything.  Either way each
  logical request is delivered exactly once — orphaned work on a
  half-dead replica is discarded, never double-served.

* **KV block migration & disaggregated prefill/decode.**  Replicas
  advertise a ROLE (``prefill`` / ``decode`` / ``mixed``) in their
  probes.  With ``RouterPolicy(disaggregate=True)`` a new prompt is
  chunked-prefilled on a prefill-role replica, its warm KV blocks are
  exported block-granular and imported into a decode-role replica,
  and the stream finishes there — token-identical to a single mixed
  replica serving it whole.  ``rebalance()`` preempts a LIVE stream
  off a hot replica the same way: the victim's blocked waiter catches
  the migration payload (``StreamMigrated``) and the router re-lands
  it on a peer — the same logical request continues, delivered
  exactly once.  On an affinity miss, ``prefix_warm=True`` pulls the
  affinity target's cached prefix blocks into the chosen replica
  before dispatching (cross-replica prefix warming).  The
  ``migrate_export`` / ``migrate_import`` transport ops carry the
  ``migrate_wire`` fault site on the same per-replica operation
  counter as the ``net_*`` sites — a seeded wire loss mid-migration
  replays exactly like every other injected fault.

Everything is observable: ``route.pick`` / ``route.retry`` /
``route.hedge`` / ``probe`` spans in the router's own tracer,
``router.*`` metrics (retries, failovers, hedges, affinity hits,
per-replica health and breaker-state gauges) in the monitor registry,
and a bounded structured ``route_log()`` whose entries are a pure
function of the seed + the replica fault schedule — seeded chaos
storms replay the same routing decisions (tests assert it).

* **Model routing & token streaming.**  Replicas advertise their
  loaded LoRA adapter inventory (``adapters``) in probes;
  ``generate(model=...)`` restricts the pick to replicas serving that
  adapter (``UnknownModel`` — the front door's 404 — when nobody
  does).  ``generate(on_token=...)`` streams: the transport forwards
  each token the moment the replica emits it, hedging is disabled
  (two live streams cannot both win), and a mid-stream failover
  resumes on a peer with the continuation SPLICED into the same
  callback — every global token index is delivered exactly once even
  across disconnects and migrations.

Transports: ``HttpReplicaClient`` speaks to a real ``serving.httpd``
endpoint; ``InProcessReplica`` wraps a local ``Engine`` directly (the
tier-1 test / bench / single-host fleet transport) and threads the
``net_*`` fault sites of ``serving.faults`` through its own
deterministic per-replica operation counter.  ``serving.routerd``
puts an HTTP front door on the router itself.
"""
from __future__ import annotations

import hashlib
import itertools
import threading
import time
from collections import deque

from .. import monitor
from .faults import NetDisconnect, NetRefused, NetTimeout
from .kvcache import KVDtypeMismatch
from .lora import UnknownAdapter
from .request import Rejected
from .stream import TokenStream, parse_sse

# -- replica health states (the probe classifier's vocabulary) ----------
HEALTHY = "healthy"      # probing clean; full routing weight
DEGRADED = "degraded"    # one failed probe / watchdog_fired: routable
#   only when no healthy replica can take the request
DRAINING = "draining"    # replica reported draining (stop(drain=True)
#   in progress): finishing its streams, gets NO new requests
DEAD = "dead"            # dead_after consecutive probe failures: not
#   routable; in-flight waiters abandon and fail over

# numeric codes for the per-replica health gauge (alert on < 3)
HEALTH_CODE = {DEAD: 0, DRAINING: 1, DEGRADED: 2, HEALTHY: 3}

# circuit breaker states + gauge codes (alert on > 0)
CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
BREAKER_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class RouterError(RuntimeError):
    """Base of router-side request failures."""


class NoReplicasAvailable(RouterError):
    """Every registered replica is dead, draining, or breaker-open."""


class UnknownModel(RouterError):
    """``generate(model=...)`` named an adapter NO registered replica
    advertises in its probed inventory — the caller's fault (the HTTP
    front door maps it to 404 ``{"reason": "unknown_adapter"}``),
    never retried."""


class RequestFailed(RouterError):
    """The request exhausted its retries (or hit a non-retryable
    replica error); ``cause`` is the last replica-side exception."""

    def __init__(self, msg, cause=None):
        super().__init__(msg)
        self.cause = cause


class ReplicaUnavailable(RuntimeError):
    """Replica-side load shed (HTTP 503/429 shaped): retryable, with
    the replica's own computed ``retry_after`` honored by the backoff.
    """

    def __init__(self, msg, status=503, retry_after=None, reason=None):
        super().__init__(msg)
        self.status = int(status)
        self.retry_after = retry_after
        self.reason = reason


class ReplicaHTTPError(RuntimeError):
    """Non-shed replica HTTP error; 4xx are the CALLER's fault and
    never retried, 5xx retry."""

    def __init__(self, msg, status, reason=None):
        super().__init__(msg)
        self.status = int(status)
        self.reason = reason


class ReplicaAbandoned(RuntimeError):
    """The transport abandoned a QUEUED-BUT-UNSTARTED request because
    the prober declared its replica dead (or the router is stopping):
    nothing was emitted, so the failover re-dispatches it whole."""


class StreamMigrated(RuntimeError):
    """The replica MIGRATED this stream out mid-decode (a rebalance
    landed on it): ``payload`` is the block-granular KV + resume
    snapshot the router re-lands on a peer, ``emitted`` is everything
    the stream had produced (the salvage fallback when no peer will
    take the payload).  NOT a failure — the replica did exactly what
    it was told."""

    def __init__(self, msg, payload=None, emitted=None):
        super().__init__(msg)
        self.payload = payload
        self.emitted = [int(t) for t in (emitted or [])]


def affinity_key(prompt, block_size):
    """Stable hash of the first ``block_size``-aligned span of the
    prompt — the prefix-cache granularity: two prompts sharing their
    aligned head (a system prompt) hash equal and route together,
    landing on the replica whose ``PrefixCache`` holds those blocks.
    Prompts shorter than one block hash whole (they still benefit from
    co-locating identical short prompts)."""
    ids = [int(t) for t in prompt]
    bs = max(int(block_size), 1)
    n = (len(ids) // bs) * bs
    span = ids[:n] if n else ids
    return hashlib.blake2b(",".join(map(str, span)).encode(),
                           digest_size=16).digest()


def _u01(seed, *parts):
    """Deterministic uniform draw in [0, 1) from (seed, parts) — the
    same blake2b construction as faults.FaultInjector, so every jitter
    the router applies (backoff spread, probe stagger, random-routing
    picks) is a pure function of its seed."""
    h = hashlib.blake2b(
        ":".join(str(p) for p in (seed,) + parts).encode(),
        digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


class CircuitBreaker:
    """Per-replica circuit breaker.

    CLOSED counts consecutive failures; ``threshold`` of them TRIP to
    OPEN (all traffic skips the replica).  After ``cooldown_s`` the
    next admission probe moves to HALF_OPEN, which admits exactly ONE
    trial request: success closes the breaker, failure re-opens it
    (fresh cooldown).  A clean HEALTH PROBE against an elapsed OPEN
    breaker also moves it to HALF_OPEN — probe-driven recovery, so a
    replica that came back is re-admitted even with no traffic to
    spend on trials.  Thread-safe; ``on_transition`` (state str) fires
    outside the decision itself but under the breaker lock, so
    transition ORDER is exact."""

    def __init__(self, threshold=3, cooldown_s=1.0, on_transition=None):
        self.threshold = max(int(threshold), 1)
        self.cooldown_s = float(cooldown_s)
        self.state = CLOSED
        self.failures = 0          # consecutive, CLOSED state only
        self.opened_at = None
        self.trips = 0
        self._trial_inflight = False
        self._lock = threading.Lock()
        self._on_transition = on_transition

    def _set(self, state, now=None):
        if state == self.state:
            return
        self.state = state
        if state == OPEN:
            self.opened_at = time.monotonic() if now is None else now
            self.trips += 1
        if self._on_transition is not None:
            self._on_transition(state)

    def _cooled(self, now):
        return (self.opened_at is None
                or now - self.opened_at >= self.cooldown_s)

    def peek(self, now=None):
        """Would a request be admitted right now? (pure — no
        half-open slot is consumed)"""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                return self._cooled(now)
            return not self._trial_inflight  # HALF_OPEN: one trial

    def acquire(self, now=None):
        """Admit a request (consumes the half-open trial slot when in
        recovery).  Returns False when the breaker blocks it."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if not self._cooled(now):
                    return False
                self._set(HALF_OPEN, now)
            if self._trial_inflight:
                return False
            self._trial_inflight = True
            return True

    def record_success(self):
        with self._lock:
            self.failures = 0
            self._trial_inflight = False
            if self.state != CLOSED:
                self._set(CLOSED)

    def record_failure(self, now=None):
        now = time.monotonic() if now is None else now
        with self._lock:
            self._trial_inflight = False
            if self.state == HALF_OPEN:
                self._set(OPEN, now)   # failed trial: fresh cooldown
                return
            self.failures += 1
            if self.state == CLOSED and self.failures >= self.threshold:
                self._set(OPEN, now)

    def release_trial(self):
        """Hand back an admitted HALF_OPEN trial slot without judging
        the replica: the attempt was cancelled by the ROUTER (hedge
        loser, shutdown), so its outcome says nothing — the next
        request becomes the trial instead of the state wedging."""
        with self._lock:
            self._trial_inflight = False

    def on_probe_success(self, now=None):
        """A clean health probe: if the breaker is OPEN and cooled,
        move to HALF_OPEN so the next real request is the trial —
        probe-driven recovery (an idle replica would otherwise stay
        tripped forever)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.state == OPEN and self._cooled(now):
                self._set(HALF_OPEN, now)


class RouterPolicy:
    """Router tuning knobs (every default is production-shaped; tests
    shrink the time constants).

    probe_interval_s / probe_jitter : background prober period and its
        +/- fractional seeded jitter (probes from N routers must not
        synchronize into thundering herds).
    dead_after : consecutive failed probes before a replica is DEAD
        (the first failure only degrades it).
    retry_max : re-dispatch attempts after the first (so a request
        touches at most ``retry_max + 1`` replicas).
    backoff_base_s / backoff_cap_s / backoff_jitter : exponential
        backoff ``min(cap, base * 2^n)`` with +/- ``jitter`` fraction
        of seeded spread; a replica's ``Retry-After`` hint raises the
        wait when larger.  Failovers off a DEAD replica skip the
        backoff — the work is not failing, the host is.
    hedge / hedge_after_s : tail-latency hedging for IDEMPOTENT
        requests (greedy, or explicitly seeded).  ``None`` derives the
        delay from the router's own request-latency p99 (falling back
        to ``hedge_floor_s`` until enough samples exist).
    breaker_threshold / breaker_cooldown_s : CircuitBreaker knobs.
    affinity : True = prefix-affinity with least-loaded fallback;
        False = seeded RANDOM routing (the bench's baseline arm).
    affinity_queue_threshold : probed queue_depth beyond which the
        affinity target is considered overloaded and the pick falls
        back to least-loaded (cache locality must not create a hot
        shard).
    disaggregate : route each NEW prompt through a prefill-role
        replica (chunked prefill + first token), migrate its warm KV
        blocks to a decode-role replica, and finish the stream there.
        Fleets with no prefill/decode split fall back to normal
        routing per-request — the knob degrades, it never strands.
    prefix_warm : on an affinity MISS, pull the affinity target's
        cached prefix blocks into the chosen replica before
        dispatching (cross-replica prefix warming; best-effort).
    request_timeout_s : per-attempt transport timeout.
    seed : the determinism root for every jitter draw.
    """

    def __init__(self, probe_interval_s=1.0, probe_jitter=0.5,
                 dead_after=3, retry_max=3, backoff_base_s=0.05,
                 backoff_cap_s=2.0, backoff_jitter=0.5, hedge=False,
                 hedge_after_s=None, hedge_floor_s=0.1,
                 breaker_threshold=3, breaker_cooldown_s=1.0,
                 affinity=True, affinity_queue_threshold=8,
                 disaggregate=False, prefix_warm=False,
                 request_timeout_s=60.0, seed=0):
        if dead_after < 1:
            raise ValueError(f"dead_after must be >= 1, got {dead_after}")
        if retry_max < 0:
            raise ValueError(f"retry_max must be >= 0, got {retry_max}")
        if breaker_cooldown_s < 0:
            raise ValueError("breaker_cooldown_s must be >= 0, got "
                             f"{breaker_cooldown_s}")
        self.probe_interval_s = float(probe_interval_s)
        self.probe_jitter = float(probe_jitter)
        self.dead_after = int(dead_after)
        self.retry_max = int(retry_max)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.backoff_jitter = float(backoff_jitter)
        self.hedge = bool(hedge)
        self.hedge_after_s = hedge_after_s
        self.hedge_floor_s = float(hedge_floor_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.affinity = bool(affinity)
        self.affinity_queue_threshold = int(affinity_queue_threshold)
        self.disaggregate = bool(disaggregate)
        self.prefix_warm = bool(prefix_warm)
        self.request_timeout_s = float(request_timeout_s)
        self.seed = int(seed)


class Replica:
    """One registry entry: transport client + probed state + breaker.
    ``signals`` is the latest probe's load view (queue_depth,
    slots_free, kv_blocks_free, drain_rate_tps, ...)."""

    def __init__(self, name, client, breaker):
        self.name = str(name)
        self.client = client
        self.breaker = breaker
        self.state = HEALTHY     # optimistic until the first probe —
        #   a router must route before its prober's first sweep
        self.signals = {}
        self.probe_failures = 0  # consecutive
        self.last_probe_at = None
        self.incarnation = None  # supervisor restart generation from
        #   the last applied probe: a LOWER probe is a stale read from
        #   a dead predecessor on the same URL and is discarded; a
        #   HIGHER one resets breaker + health history atomically
        self.inflight = 0        # guarded: handler + hedge threads
        self._inflight_lock = threading.Lock()

    def track(self, delta):
        with self._inflight_lock:
            self.inflight += delta

    @property
    def role(self):
        """Probed serving role: ``prefill`` / ``decode`` / ``mixed``.
        Unprobed replicas default to mixed — routable everywhere, so
        a fleet with no role split behaves exactly as before."""
        return self.signals.get("role") or "mixed"

    def load_key(self):
        """Least-loaded ordering: probed queue depth first, then the
        fewest free slots LAST (more headroom wins), name as the
        deterministic tiebreak."""
        q = self.signals.get("queue_depth")
        free = self.signals.get("slots_free")
        return (q if q is not None else 0,
                -(free if free is not None else 0), self.name)

    def view(self):
        """JSON-shaped registry row (the routerd /replicas surface)."""
        return {
            "name": self.name, "state": self.state, "role": self.role,
            "breaker": self.breaker.state,
            "breaker_trips": self.breaker.trips,
            "probe_failures": self.probe_failures,
            "incarnation": self.incarnation,
            "inflight": self.inflight,
            "address": getattr(self.client, "address", None),
            "signals": dict(self.signals),
        }


class Router:
    """Front-door tier spreading requests over N engine replicas with
    health probing, prefix affinity, retries, hedging, circuit
    breaking, and failover (module docstring has the full story).

    Parameters
    ----------
    replicas : dict name -> client, or iterable of (name, client).  A
        client implements ``probe() -> dict`` (a ``/healthz``-shaped
        health+load view) and ``generate(payload, should_abort=None)
        -> dict`` raising the classified transport errors
        (NetRefused/NetTimeout/NetDisconnect, ReplicaUnavailable,
        ReplicaHTTPError, ReplicaAbandoned).
    policy : RouterPolicy.
    kv_block_size : the affinity hash alignment.  None adopts the
        first probed replica's ``kv_block_size`` (falling back to 16)
        — the router should agree with the fleet's prefix-cache
        granularity without being told twice.
    registry : monitor.StatRegistry (default: the process default).
    tracing : keep a router-side span tracer (route.pick/route.retry/
        route.hedge/probe + request lifecycle instants).
    """

    def __init__(self, replicas=None, policy=None, kv_block_size=None,
                 registry=None, tracing=True, trace_capacity=16384):
        self.policy = policy or RouterPolicy()
        self._kv_bs = (None if kv_block_size is None
                       else int(kv_block_size))
        self.registry = registry or monitor.default_registry()
        self.tracer = (monitor.Tracer(capacity=trace_capacity)
                       if tracing else monitor.NullTracer())
        self._lock = threading.Lock()
        self._replicas = {}
        self._rids = itertools.count()
        self._probe_no = itertools.count()
        self.log = deque(maxlen=4096)   # structured routing decisions;
        #   entry ORDER is deterministic for sequential traffic, and
        #   per-request subsequences are deterministic always
        self._stopping = False
        self._probe_thread = None
        self._probe_stop = threading.Event()
        reg = self.registry
        self._m_reqs = reg.counter(
            "router.requests_total", "requests accepted by the router")
        self._m_served = reg.counter(
            "router.served_total", "requests completed and delivered")
        self._m_failed = reg.counter(
            "router.failed_total", "requests failed after classification")
        self._m_retries = reg.counter(
            "router.retries_total", "re-dispatch attempts (all causes)")
        self._m_failovers = reg.counter(
            "router.failovers_total",
            "re-dispatches caused by a dying/dead replica (abandoned "
            "queued requests + mid-stream disconnects)")
        self._m_hedges = reg.counter(
            "router.hedges_total", "hedge dispatches armed and fired")
        self._m_hedge_wins = reg.counter(
            "router.hedge_wins_total",
            "requests where the hedge finished before the primary")
        self._m_picks = reg.counter(
            "router.picks_total", "routing decisions made")
        self._m_affinity = reg.counter(
            "router.affinity_hits_total",
            "picks that landed on the prefix-affinity target")
        self._m_breaker_trips = reg.counter(
            "router.breaker_trips_total",
            "circuit breakers tripped open (all replicas)")
        self._m_migrations = reg.counter(
            "router.migrations_total",
            "streams moved between replicas by KV block migration "
            "(disaggregated prefill handoffs + rebalance re-lands)")
        self._m_probes = reg.counter(
            "router.probes_total", "health probes sent")
        self._m_lat = reg.histogram(
            "router.request_ms",
            "end-to-end request latency through the router (ms)")
        for name, client in (dict(replicas or {})).items():
            self.add_replica(name, client)

    # -- registry ------------------------------------------------------
    def add_replica(self, name, client):
        name = str(name)
        breaker = CircuitBreaker(
            threshold=self.policy.breaker_threshold,
            cooldown_s=self.policy.breaker_cooldown_s,
            on_transition=lambda st, n=name: self._breaker_event(n, st))
        rep = Replica(name, client, breaker)
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already registered")
            self._replicas[name] = rep
        self._gauge_health(rep)
        self._gauge_breaker(name, CLOSED)
        return rep

    def remove_replica(self, name):
        with self._lock:
            rep = self._replicas.pop(str(name), None)
        return rep

    def replicas(self):
        """Registry snapshot: list of ``Replica.view()`` rows."""
        with self._lock:
            reps = list(self._replicas.values())
        return [r.view() for r in reps]

    def _reps(self):
        with self._lock:
            return list(self._replicas.values())

    def _gauge_health(self, rep):
        self.registry.gauge(
            f"router.replica_health.{rep.name}",
            "replica health (0 dead / 1 draining / 2 degraded / "
            "3 healthy)").set(HEALTH_CODE[rep.state])

    def _gauge_breaker(self, name, state):
        self.registry.gauge(
            f"router.breaker_state.{name}",
            "circuit breaker (0 closed / 1 half-open / 2 open)"
        ).set(BREAKER_CODE[state])

    def _breaker_event(self, name, state):
        self._gauge_breaker(name, state)
        if state == OPEN:
            self._m_breaker_trips.inc()
        self.log.append(("breaker", name, state))
        self.tracer.instant("router.breaker", cat="router",
                            replica=name, state=state)

    # -- health probing ------------------------------------------------
    def _probe_is_stale(self, rep, info):
        """True when a probe body carries a LOWER incarnation than the
        registry already applied for this replica — a read that left
        the dead predecessor before it died, arriving after the
        supervisor already respawned a successor on the same URL.
        Applying it would poison the successor's state."""
        inc = info.get("incarnation")
        if inc is None or rep.incarnation is None:
            return False
        if int(inc) >= rep.incarnation:
            return False
        self.log.append(("stale_probe", rep.name, int(inc)))
        self.tracer.instant("router.stale_probe", cat="router",
                            replica=rep.name, incarnation=int(inc),
                            current=rep.incarnation)
        return True

    def _apply_incarnation(self, rep, inc):
        """Record a probed incarnation.  A replica returning on the
        same URL as a NEW incarnation gets its circuit breaker and
        health history reset ATOMICALLY — a fresh CircuitBreaker is
        swapped in (attribute assignment: atomic under the GIL), so
        the successor starts CLOSED with zero failures instead of
        inheriting half-open/open state, while in-flight attempts
        still hold the predecessor's breaker object and their stale
        failures land there harmlessly."""
        if rep.incarnation is not None and inc > rep.incarnation:
            rep.breaker = CircuitBreaker(
                threshold=self.policy.breaker_threshold,
                cooldown_s=self.policy.breaker_cooldown_s,
                on_transition=lambda st, n=rep.name:
                    self._breaker_event(n, st))
            rep.probe_failures = 0
            self._gauge_breaker(rep.name, CLOSED)
            self.log.append(("incarnation", rep.name, inc))
            self.tracer.instant("router.incarnation", cat="router",
                                replica=rep.name, incarnation=inc)
        if rep.incarnation != inc:
            rep.incarnation = inc
            self.registry.gauge(
                f"router.replica_incarnation.{rep.name}",
                "supervisor restart generation from the last applied "
                "probe").set(inc)

    def classify_probe(self, info):
        """Map a ``/healthz``-shaped probe body to a health state —
        the liveness/readiness split made routable: ``draining`` is
        FINISHING (stop routing, let it land its streams),
        ``watchdog_fired`` is possibly WEDGED (degrade until a clean
        probe), anything else answering at all is healthy."""
        if info.get("draining") or info.get("state") == DRAINING:
            # InProcessReplica reports a "draining" bool; httpd's
            # /healthz reports it via "state" — both mean FINISHING
            return DRAINING
        if info.get("watchdog_fired") or info.get("state") == \
                "watchdog_fired" or info.get("ready") is False:
            return DEGRADED
        return HEALTHY

    def probe_once(self, now=None):
        """One sweep: probe every replica, update state + signals +
        gauges.  Returns {name: state}.  Probes are SENT concurrently
        — one hung replica must not head-of-line block health
        detection for the whole fleet — but results are APPLIED
        serially in registry order, so the state transitions and the
        routing log stay deterministic given the probe outcomes.
        Deterministic tests call this directly; production runs it on
        the jittered prober thread."""
        reps = self._reps()
        results = [None] * len(reps)

        def _probe(i, rep):
            try:
                results[i] = (True, rep.client.probe())
            except Exception as e:
                results[i] = (False, e)

        if len(reps) == 1:
            _probe(0, reps[0])
        else:
            threads = [threading.Thread(target=_probe, args=(i, rep),
                                        daemon=True)
                       for i, rep in enumerate(reps)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        out = {}
        for rep, (answered, info) in zip(reps, results):
            self._m_probes.inc()
            with self.tracer.span("probe", cat="router",
                                  replica=rep.name) as sp:
                if not answered:
                    rep.probe_failures += 1
                    new = (DEAD if rep.probe_failures
                           >= self.policy.dead_after else DEGRADED)
                    if sp is not None and hasattr(sp, "args"):
                        sp.args["error"] = type(info).__name__
                elif self._probe_is_stale(rep, info):
                    # a probe stamped with a LOWER incarnation is the
                    # dead predecessor's last answer arriving late on
                    # the same URL: discard it WHOLE — state, signals
                    # and breaker stay the successor's
                    new = rep.state
                else:
                    inc = info.get("incarnation")
                    if inc is not None:
                        self._apply_incarnation(rep, int(inc))
                    rep.probe_failures = 0
                    rep.signals.update(
                        {k: info.get(k) for k in
                         ("queue_depth", "slots_free",
                          "kv_blocks_free", "drain_rate_tps",
                          "slots_total", "kv_block_size",
                          # mesh-sharded replicas advertise their
                          # full (mp, dp) shape: the /replicas
                          # registry rows (and timeline.py --router)
                          # label sharded replicas without a second
                          # probe protocol
                          "mesh_shape", "mp", "dp",
                          # quantized serving: dtype labels + block
                          # byte split, so migration can pre-filter
                          # kv_dtype-mismatched peers from the
                          # registry instead of burning an import
                          # round-trip on a guaranteed 400
                          "weight_dtype", "kv_dtype",
                          "kv_block_bytes", "kv_scale_bytes",
                          # disaggregated fleets advertise each
                          # replica's serving role the same way,
                          # and supervised ones their restart
                          # generation
                          "role", "incarnation",
                          # multi-LoRA serving: the adapter inventory
                          # is what pick(model=...) routes on, and
                          # live stream counts label the fleet in
                          # timeline.py --router
                          "adapters", "streams_active",
                          # kernel variant + long-context exposure:
                          # which ragged kernel body the replica
                          # serves (stream vs gather A/B) and the max
                          # context length it has actually reached
                          "attn_impl", "max_context_len",
                          # host-RAM offload tier: how much warm KV a
                          # replica holds PAST its device pool — the
                          # warmth prefix_warm taps before recompute
                          "kv_host_blocks", "kv_host_bytes",
                          "kv_host_capacity_mb",
                          "offload_hit_tokens_total")})
                    if self._kv_bs is None \
                            and info.get("kv_block_size"):
                        self._kv_bs = int(info["kv_block_size"])
                    new = self.classify_probe(info)
                    # probe-driven breaker recovery: the replica
                    # answers again, so an elapsed OPEN breaker may
                    # move to HALF_OPEN and trial real traffic
                    if new in (HEALTHY, DEGRADED):
                        rep.breaker.on_probe_success()
                if sp is not None and hasattr(sp, "args"):
                    sp.args["state"] = new
            rep.last_probe_at = time.monotonic() if now is None else now
            if new != rep.state:
                self.log.append(("probe", rep.name, new))
                self.tracer.instant("router.replica_state",
                                    cat="router", replica=rep.name,
                                    state=new, was=rep.state)
            rep.state = new
            self._gauge_health(rep)
            out[rep.name] = new
        return out

    def mark_dead(self, name):
        """Operator/test override: declare a replica dead NOW (its
        queued-but-unstarted requests abandon and fail over on their
        next poll)."""
        with self._lock:
            rep = self._replicas.get(str(name))
        if rep is None:
            raise KeyError(f"no replica {name!r}")
        if rep.state != DEAD:
            self.log.append(("probe", rep.name, DEAD))
        rep.state = DEAD
        rep.probe_failures = max(rep.probe_failures,
                                 self.policy.dead_after)
        self._gauge_health(rep)

    def start(self):
        """Run the prober on a daemon thread (jittered interval)."""
        if self._probe_thread is not None \
                and self._probe_thread.is_alive():
            return self
        self._stopping = False
        self._probe_stop = threading.Event()
        stop = self._probe_stop

        def loop():
            while not stop.is_set():
                try:
                    self.probe_once()
                except Exception:
                    pass  # the prober must outlive any one bad client
                n = next(self._probe_no)
                j = self.policy.probe_jitter
                scale = 1.0 + j * (_u01(self.policy.seed, "probe", n)
                                   - 0.5)
                stop.wait(self.policy.probe_interval_s * scale)

        self._probe_thread = threading.Thread(
            target=loop, daemon=True, name="paddle_tpu-router-prober")
        self._probe_thread.start()
        return self

    def stop(self):
        """Stop the prober and abandon in-flight waits (their
        transports see ``should_abort`` fire)."""
        self._stopping = True
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
            self._probe_thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- routing -------------------------------------------------------
    def block_size(self):
        return self._kv_bs or 16

    def _affinity_target(self, key, reps):
        """Rendezvous (HRW) hash: every replica scores the key, the
        max wins — adding/removing a replica only remaps the keys that
        scored it highest, so the fleet's prefix caches stay warm
        through churn."""
        best = None
        for r in reps:
            h = hashlib.blake2b(key + r.name.encode(),
                                digest_size=8).digest()
            score = int.from_bytes(h, "big")
            if best is None or score > best[0]:
                best = (score, r)
        return best[1] if best else None

    def pick(self, prompt, exclude=(), rid=None, attempt=0,
             phase=None, model=None):
        """One routing decision: (replica, how) where how is
        ``affinity`` / ``load`` / ``random`` / ``last_resort``.
        ``phase`` (``prefill`` / ``decode``) restricts the candidate
        set to replicas of that ROLE — exact-role replicas when any
        exist, else role-or-mixed; a phase slice with nothing
        routable falls back to the whole fleet (disaggregation
        degrades before it fails).  ``model`` restricts it to
        replicas whose probed adapter inventory lists that LoRA
        adapter — UnknownModel when NO replica advertises it (the
        fleet genuinely cannot serve it), NoReplicasAvailable when
        some do but none is routable right now (retryable).  Raises
        NoReplicasAvailable when nothing at all is routable."""
        key = affinity_key(prompt, self.block_size())
        exclude = set(exclude)
        reps = self._reps()
        if model is not None:
            have = [r for r in reps
                    if model in (r.signals.get("adapters") or ())]
            if not have:
                raise UnknownModel(
                    f"no replica among {len(reps)} advertises "
                    f"adapter {model!r}")
            reps = have
        if phase is not None:
            exact = [r for r in reps if r.role == phase]
            reps = exact or [r for r in reps
                             if r.role in (phase, "mixed")] or reps
        healthy = [r for r in reps if r.name not in exclude
                   and r.state == HEALTHY and r.breaker.peek()]
        degraded = [r for r in reps if r.name not in exclude
                    and r.state == DEGRADED and r.breaker.peek()]
        pool, how = healthy, None
        if not pool:
            pool, how = degraded, "last_resort"
        if not pool:
            # everything routable was excluded by earlier failed
            # attempts: retrying a suspect replica beats failing the
            # request outright
            pool = [r for r in reps
                    if r.state in (HEALTHY, DEGRADED)
                    and r.breaker.peek()]
            how = "last_resort"
        if not pool:
            if phase is not None:
                # the role slice is unroutable: degrade to whole-
                # fleet routing before failing the request outright
                return self.pick(prompt, exclude, rid, attempt,
                                 model=model)
            raise NoReplicasAvailable(
                f"no routable replica among {len(reps)}: "
                + ", ".join(f"{r.name}={r.state}/{r.breaker.state}"
                            for r in reps))
        if not self.policy.affinity:
            # seeded random (the bench's baseline arm): deterministic
            # per (seed, request, attempt)
            pool = sorted(pool, key=lambda r: r.name)
            idx = int(_u01(self.policy.seed, "random", rid, attempt)
                      * len(pool)) % len(pool)
            return pool[idx], (how or "random")
        target = self._affinity_target(key, reps)
        if how is None and target is not None and target in pool:
            q = target.signals.get("queue_depth")
            if q is None or q <= self.policy.affinity_queue_threshold:
                return target, "affinity"
        chosen = min(pool, key=lambda r: r.load_key())
        return chosen, (how or "load")

    # -- the request path ----------------------------------------------
    def _classify(self, exc, idempotent):
        """(kind, retryable, retry_after, emitted) for a replica-side
        exception — the retry policy's one decision table."""
        if isinstance(exc, ReplicaAbandoned):
            return "abandoned", True, None, None
        if isinstance(exc, NetDisconnect):
            return "disconnect", True, None, exc.emitted
        if isinstance(exc, NetRefused):
            return "refused", True, None, None
        if isinstance(exc, NetTimeout):
            # the request may have EXECUTED (loss on the response
            # path): only idempotent work is blindly re-sent
            return "timeout", idempotent, None, None
        if isinstance(exc, ReplicaUnavailable):
            return "unavailable", True, exc.retry_after, None
        if isinstance(exc, ReplicaHTTPError):
            return (f"http_{exc.status}", exc.status >= 500, None,
                    None)
        return type(exc).__name__, False, None, None

    def _backoff(self, rid, n, hint=None):
        d = min(self.policy.backoff_cap_s,
                self.policy.backoff_base_s * (2.0 ** n))
        j = self.policy.backoff_jitter
        d *= 1.0 + j * (_u01(self.policy.seed, "backoff", rid, n) - 0.5)
        if hint is not None:
            d = max(d, float(hint))
        return d

    def _hedge_delay(self):
        if self.policy.hedge_after_s is not None:
            return float(self.policy.hedge_after_s)
        if self._m_lat.count >= 20:
            return max(self._m_lat.percentile(99) / 1e3,
                       self.policy.hedge_floor_s)
        return self.policy.hedge_floor_s

    def _attempt(self, rep, payload, rid, abort_extra=None,
                 op="generate", on_token=None):
        """One dispatch against one replica: inflight accounting,
        breaker bookkeeping, abandon hook.  ``op`` names the client
        method (``generate`` / ``migrate_export`` /
        ``migrate_import``) — all share the transport contract.
        ``on_token`` (generate only) asks the transport to STREAM:
        it fires per token as the replica emits it."""

        def should_abort():
            return (self._stopping or rep.state == DEAD
                    or (abort_extra is not None and abort_extra()))

        kw = {"should_abort": should_abort}
        if on_token is not None and op == "generate":
            kw["on_token"] = on_token
        rep.track(+1)
        try:
            resp = getattr(rep.client, op)(payload, **kw)
        except Exception as e:
            if self._stopping \
                    or (abort_extra is not None and abort_extra()):
                # a CANCELLED attempt (hedge loser, router shutdown)
                # is the router's own doing — it must not poison the
                # replica's breaker; just hand back any trial slot so
                # a HALF_OPEN breaker cannot wedge
                rep.breaker.release_trial()
            elif isinstance(e, StreamMigrated):
                # a rebalance the ROUTER itself ordered: the replica
                # did exactly what it was told — a health signal
                rep.breaker.record_success()
            elif isinstance(e, ReplicaHTTPError) and e.status < 500:
                # a 4xx is the CALLER's fault and PROVES the replica
                # is answering: a health signal, not a failure — a
                # bad client must not blackball a healthy replica
                rep.breaker.record_success()
            else:
                rep.breaker.record_failure()
            raise
        else:
            rep.breaker.record_success()
            return resp
        finally:
            rep.track(-1)

    def _hedged_attempt(self, rep, payload, rid, prompt, exclude):
        """Primary + optional delayed hedge; first SUCCESS wins and
        cancels the loser (abort flag -> the transport abandons or
        disconnects it; orphaned replica work is discarded).  Returns
        ``(winner, response, hedged)`` — ``hedged`` True when the
        second dispatch actually fired, so "attempts" can keep
        counting DISPATCHES — or raises the primary's error (hedge
        errors never mask a primary success and vice versa)."""
        delay = self._hedge_delay()
        results = {}
        done = threading.Condition()
        cancel = {"primary": False, "hedge": False}

        def run(slot, r, pl):
            try:
                res = self._attempt(r, pl, rid,
                                    abort_extra=lambda: cancel[slot])
            except Exception as e:  # delivered as a value
                res = e
            with done:
                results[slot] = (r, res)
                done.notify_all()

        t1 = threading.Thread(target=run,
                              args=("primary", rep, payload),
                              daemon=True)
        t1.start()
        with done:
            done.wait_for(lambda: "primary" in results, timeout=delay)
        if "primary" not in results:
            try:
                hedge_rep, how = self.pick(
                    prompt, exclude=exclude | {rep.name}, rid=rid,
                    attempt=-1)
            except NoReplicasAvailable:
                hedge_rep = None
            if hedge_rep is not None \
                    and not hedge_rep.breaker.acquire():
                # the hedge must respect the half-open single-trial
                # invariant like any other dispatch; a hedge is never
                # worth racing a recovery trial
                hedge_rep = None
            if hedge_rep is not None:
                self._m_hedges.inc()
                self.log.append(("hedge", rid, hedge_rep.name))
                with self.tracer.span("route.hedge", cat="router",
                                      req=rid, primary=rep.name,
                                      hedge=hedge_rep.name,
                                      delay_ms=round(delay * 1e3, 3)):
                    t2 = threading.Thread(
                        target=run,
                        args=("hedge", hedge_rep, dict(payload)),
                        daemon=True)
                    t2.start()
                    with done:
                        done.wait_for(
                            lambda: self._hedge_settled(results))
                win_slot = self._hedge_winner(results)
                lose_slot = ("hedge" if win_slot == "primary"
                             else "primary")
                cancel[lose_slot] = True
                if win_slot == "hedge":
                    self._m_hedge_wins.inc()
                    self.log.append(
                        ("hedge_win", rid, results["hedge"][0].name))
                r, res = results[win_slot]
                if isinstance(res, Exception):
                    raise res
                return r, res, True
        with done:
            done.wait_for(lambda: "primary" in results)
        r, res = results["primary"]
        if isinstance(res, Exception):
            raise res
        return r, res, False

    @staticmethod
    def _hedge_settled(results):
        """Wait is over once anyone SUCCEEDED or everyone failed."""
        succ = [s for s, (_, res) in results.items()
                if not isinstance(res, Exception)]
        return bool(succ) or len(results) == 2

    @staticmethod
    def _hedge_winner(results):
        succ = [s for s, (_, res) in results.items()
                if not isinstance(res, Exception)]
        if succ:
            # primary wins ties (it was dispatched first)
            return "primary" if "primary" in succ else "hedge"
        return "primary"

    # -- KV block migration ---------------------------------------------
    def _disagg_split(self, exclude):
        """True when the routable fleet (minus ``exclude``) still has
        BOTH a prefill-role and a decode-role replica — the
        precondition for a disaggregated dispatch."""
        roles = {r.role for r in self._reps()
                 if r.name not in exclude
                 and r.state in (HEALTHY, DEGRADED)
                 and r.breaker.peek()}
        return "prefill" in roles and "decode" in roles

    def _import_stream(self, mig_payload, rid, prompt, exclude,
                       timeout_s, phase="decode"):
        """Land a migration payload on a routable replica (decode
        role preferred) and block until the resumed stream completes.
        Returns ``(replica, resp, dispatches)``; ``resp`` is None
        when every candidate refused the payload.  Safe to retry the
        SAME payload across candidates: a failed import adopts
        nothing (the engine rolls its blocks back to refcount 0), and
        a destination that died mid-resume never delivered — re-
        importing replays the identical continuation from the
        migration point, so nothing is duplicated."""
        body = dict(mig_payload)
        body["timeout_s"] = timeout_s
        tried = set(exclude)
        # quantized serving: a peer whose probed kv_dtype disagrees
        # with the payload's would reject the import with a
        # kv_dtype_mismatch 400 anyway — pre-filter it from the
        # candidate set (unknown signals pass: the import's own
        # validation stays the source of truth)
        want_dtype = (mig_payload.get("kv") or {}).get("dtype")
        if want_dtype is not None:
            for r in self._reps():
                have = r.signals.get("kv_dtype")
                if have is not None and str(have) != str(want_dtype):
                    tried.add(r.name)
        n = 0
        for k in range(self.policy.retry_max + 1):
            try:
                with self.tracer.span("route.pick", cat="router",
                                      req=rid, attempt=k,
                                      phase=phase) as sp:
                    rep, how = self.pick(prompt, exclude=tried,
                                         rid=rid, attempt=k,
                                         phase=phase)
                    if not rep.breaker.acquire():
                        raise ReplicaUnavailable(
                            f"{rep.name} breaker trial already in "
                            "flight")
                    if sp is not None and hasattr(sp, "args"):
                        sp.args.update(replica=rep.name, how=how)
            except (NoReplicasAvailable, ReplicaUnavailable):
                break
            self._m_picks.inc()
            self.log.append(("pick", rid, rep.name,
                             f"{phase}/{how}", k))
            n += 1
            try:
                resp = self._attempt(rep, body, rid,
                                     op="migrate_import")
            except Exception as e:
                kind, _, _, _ = self._classify(e, True)
                self.log.append(("failover", rid, rep.name,
                                 f"import_{kind}"))
                self._m_retries.inc()
                tried.add(rep.name)
                continue
            return rep, resp, n
        return None, None, n

    def _disagg_attempt(self, payload, rid, prompt, exclude,
                        emitted_sink):
        """One disaggregated dispatch: chunked prefill + first token
        on a PREFILL-role replica, migrate the warm KV blocks, finish
        the stream on a DECODE-role replica.  Returns ``(served_by,
        resp, dispatches)`` on success or None on failure — the
        caller's normal path then takes over (``exclude`` and the
        greedy ``emitted_sink`` are updated in place, so a resumed
        stream picks up exactly where the wreckage left it)."""
        try:
            with self.tracer.span("route.pick", cat="router", req=rid,
                                  phase="prefill") as sp:
                pre, how = self.pick(prompt, exclude=exclude, rid=rid,
                                     attempt=0, phase="prefill")
                if not pre.breaker.acquire():
                    raise ReplicaUnavailable(
                        f"{pre.name} breaker trial already in flight")
                if sp is not None and hasattr(sp, "args"):
                    sp.args.update(replica=pre.name, how=how)
        except (NoReplicasAvailable, ReplicaUnavailable):
            return None
        self._m_picks.inc()
        if how == "affinity":
            self._m_affinity.inc()
        self.log.append(("pick", rid, pre.name, f"prefill/{how}", 0))
        body = dict(payload)
        body["min_tokens"] = 1
        try:
            res = self._attempt(pre, body, rid, op="migrate_export")
        except Exception as e:
            kind, _, _, got = self._classify(e, True)
            self.log.append(("failover", rid, pre.name,
                             f"export_{kind}"))
            exclude.add(pre.name)
            if got and emitted_sink is not None:
                emitted_sink.extend(int(t) for t in got)
            return None
        gen0 = [int(t) for t in res.get("generated") or []]
        if res.get("completed") or res.get("payload") is None:
            # the stream finished on the prefill replica (EOS inside
            # the budget, or the export declined and it served the
            # request whole): nothing left to migrate
            resp = {k: v for k, v in res.items()
                    if k in ("ttft_ms", "id")}
            resp["generated"] = gen0
            return pre, resp, 1
        mig = res["payload"]
        dec, resp, n = self._import_stream(
            mig, rid, prompt, set(exclude) | {pre.name},
            payload.get("timeout_s"))
        if resp is not None:
            self._m_migrations.inc()
            self.log.append(("migrate", rid, pre.name, dec.name,
                             resp.get("migrated_blocks")))
            self.tracer.instant(
                "route.migrated", cat="router", req=rid,
                source=pre.name, dest=dec.name,
                blocks=resp.get("migrated_blocks"))
            return dec, resp, 1 + n
        # every decode replica refused the payload; the source stream
        # is already terminated, so salvage the prefill tokens — a
        # greedy stream resumes from them on the normal path, a
        # seeded one restarts from scratch (identical either way)
        if gen0 and emitted_sink is not None:
            emitted_sink.extend(gen0)
        exclude.add(pre.name)
        return None

    def _warm_prefix(self, chosen, prompt, rid):
        """Cross-replica prefix warming: on an affinity MISS, pull
        the affinity target's cached prefix blocks for this prompt
        into the replica about to serve it — its chunked prefill then
        skips the warmed span.  Best-effort by design: any failure
        just means a cold prefill."""
        reps = self._reps()
        target = self._affinity_target(
            affinity_key(prompt, self.block_size()), reps)
        if target is None or target is chosen \
                or target.state not in (HEALTHY, DEGRADED):
            return
        try:
            res = target.client.migrate_export(
                {"prefix_only": True,
                 "tokens": [int(t) for t in prompt]})
            payload = res.get("payload")
            if not payload or not payload.get("kv"):
                return
            got = chosen.client.migrate_import(payload)
            # "device" = trie blocks only; "host"/"mixed" = the
            # source's host-RAM offload tier contributed blocks the
            # destination would otherwise have recomputed
            tier = payload.get("tier", "device")
            self.log.append(("warm", rid, target.name, chosen.name,
                             got.get("blocks"), tier))
            self.tracer.instant(
                "route.prefix_warmed", cat="router", req=rid,
                source=target.name, dest=chosen.name,
                blocks=got.get("blocks"), tier=tier)
        except Exception:
            pass

    def rebalance(self, source, request_id=None, min_tokens=1,
                  timeout=10.0):
        """Preempt-and-migrate: export one LIVE stream off ``source``
        (the engine picks its lowest-priority victim when
        ``request_id`` is None), delivering the payload through the
        victim's own blocked waiter — the router thread serving that
        stream catches ``StreamMigrated`` and re-lands it on a peer,
        so the stream moves without ever being double-served.
        In-process transports only: an HTTP replica's waiter is its
        remote client, which the router cannot hand a payload to.
        Returns the export verdict dict."""
        with self._lock:
            rep = self._replicas.get(str(source))
        if rep is None:
            raise KeyError(f"no replica {source!r}")
        body = {"request_id": request_id, "deliver": "error",
                "min_tokens": int(min_tokens),
                "timeout_s": float(timeout)}
        res = rep.client.migrate_export(body)
        self.log.append(("rebalance", source,
                         bool(res.get("completed")),
                         len(res.get("generated") or [])))
        self.tracer.instant("route.rebalance", cat="router",
                            replica=source,
                            completed=bool(res.get("completed")))
        return res

    def generate(self, prompt, max_new_tokens=16, eos_token_id=None,
                 temperature=1.0, top_k=0, top_p=1.0, seed=None,
                 priority=0, tenant=None, timeout=None, model=None,
                 on_token=None):
        """Route one generation request; blocks until a replica
        delivers it (HTTP handler threads are the expected callers —
        the router is I/O-bound, not compute-bound).  Returns a dict:
        ``ids`` (prompt + generated), ``generated``, ``replica`` (the
        serving one), ``attempts``, ``req`` (router-side id), plus the
        replica's reported fields.  Raises RequestFailed /
        NoReplicasAvailable after classification + retries.

        ``model`` routes to replicas advertising that LoRA adapter
        (UnknownModel when none does).  ``on_token`` streams: it
        fires once per generated token, BY GLOBAL INDEX exactly once,
        even across failovers — a resumed greedy stream forwards only
        its continuation, a seeded restart suppresses the re-played
        prefix, and a migrated stream splices the resumed tokens in
        seamlessly.  Streaming disables hedging (two live streams
        cannot both win) and the disaggregated split (its tokens
        arrive via migration responses, not a live stream)."""
        rid = next(self._rids)
        self._m_reqs.inc()
        prompt = [int(t) for t in prompt]
        sent = 0              # tokens DELIVERED to on_token, by index

        def _deliver(toks, base):
            # exactly-once by global token index: forward only the
            # indices the caller has not seen yet (salvaged prefixes
            # and seeded replays are suppressed, gaps are impossible
            # because every source is a contiguous run from its base)
            nonlocal sent
            if on_token is None:
                return
            for i, tok in enumerate(toks):
                g = base + i
                if g >= sent:
                    on_token(int(tok))
                    sent = g + 1
        do_sample = (int(top_k or 0) > 0 or float(temperature) != 1.0
                     or float(top_p) < 1.0)
        idempotent = (not do_sample) or seed is not None
        greedy = not do_sample
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        self.tracer.instant("route.accepted", cat="router", req=rid,
                            prompt=len(prompt), max_new=max_new_tokens)
        t0 = time.monotonic()
        emitted = []          # tokens salvaged across disconnects
        exclude = set()       # replicas that failed THIS request
        attempt = 0
        last_exc = None
        while True:
            if deadline is not None and time.monotonic() > deadline:
                self._m_failed.inc()
                raise RequestFailed(
                    f"request {rid} ran out its {timeout}s budget "
                    f"after {attempt} attempt(s)", cause=last_exc)
            remaining = max_new_tokens - len(emitted)
            if remaining <= 0 or (eos_token_id is not None and emitted
                                  and emitted[-1] == int(eos_token_id)):
                # the disconnect arrived AFTER the final token (budget
                # spent, or the salvaged tail already ends in EOS):
                # the stream is whole, nothing to re-dispatch — a
                # resumed attempt would generate PAST the EOS.
                # ``attempt`` was already bumped past the disconnect,
                # so hand _serve the index of the LAST dispatch made —
                # "attempts" must count dispatches, not loop turns
                _deliver(emitted, 0)
                return self._serve(rid, prompt, emitted, [], None,
                                   attempt - 1, t0)
            attempt_timeout = self.policy.request_timeout_s
            if deadline is not None:
                # one slow attempt must not overrun the caller's
                # budget: the transport deadline shrinks with it
                attempt_timeout = min(
                    attempt_timeout,
                    max(deadline - time.monotonic(), 0.001))
            payload = {
                "prompt": prompt + emitted,
                "max_new_tokens": remaining,
                "eos_token_id": eos_token_id,
                "temperature": temperature, "top_k": top_k,
                "top_p": top_p, "seed": seed, "priority": priority,
                "tenant": tenant,
                "timeout_s": attempt_timeout,
            }
            if model is not None:
                payload["adapter"] = model
            fwd = None
            if on_token is not None:
                # catch the caller up on anything salvaged since the
                # last dispatch, then hand the transport a forwarder
                # anchored at this attempt's resume point — its
                # attempt-local token i is global index base + i
                _deliver(emitted, 0)
                _base = len(emitted)
                _ctr = itertools.count()

                def fwd(tok, _b=_base, _c=_ctr):
                    _deliver([tok], _b + next(_c))
            if self.policy.disaggregate and on_token is None \
                    and model is None \
                    and self._disagg_split(exclude):
                out = self._disagg_attempt(
                    payload, rid, prompt, exclude,
                    emitted if greedy else None)
                if out is not None:
                    served_by, resp, n = out
                    return self._serve(rid, prompt, emitted,
                                       resp.get("generated", []),
                                       served_by, attempt + n - 1,
                                       t0, resp)
                # the disaggregated attempt burned out (exclude and
                # any greedy salvage were updated in place): next
                # turn retries — another split if one is still
                # routable, the normal path otherwise
                self._m_retries.inc()
                attempt += 1
                continue
            try:
                with self.tracer.span("route.pick", cat="router",
                                      req=rid, attempt=attempt) as sp:
                    rep, how = self.pick(prompt, exclude=exclude,
                                         rid=rid, attempt=attempt,
                                         model=model)
                    if not rep.breaker.acquire():
                        # raced a concurrent half-open trial: treat as
                        # a retryable miss
                        raise ReplicaUnavailable(
                            f"{rep.name} breaker trial already in "
                            "flight", retry_after=None)
                    if sp is not None and hasattr(sp, "args"):
                        sp.args.update(replica=rep.name, how=how)
                self._m_picks.inc()
                if how == "affinity":
                    self._m_affinity.inc()
                self.log.append(("pick", rid, rep.name, how, attempt))
                if self.policy.prefix_warm and how != "affinity" \
                        and not emitted:
                    self._warm_prefix(rep, prompt, rid)
                use_hedge = (self.policy.hedge and idempotent
                             and attempt == 0 and on_token is None)
                hedged = False
                if use_hedge:
                    served_by, resp, hedged = self._hedged_attempt(
                        rep, payload, rid, prompt, exclude)
                else:
                    resp = self._attempt(rep, payload, rid,
                                         on_token=fwd)
                    served_by = rep
            except (NoReplicasAvailable, UnknownModel):
                self._m_failed.inc()
                raise
            except StreamMigrated as e:
                # a rebalance kicked this stream off its replica mid-
                # decode: the payload IS the stream (KV blocks +
                # resume snapshot) — land it on a peer and the SAME
                # logical request continues there, exactly once
                self.log.append(("migrate_out", rid, rep.name,
                                 len(e.emitted)))
                dest, resp, n = None, None, 0
                if e.payload is not None:
                    dest, resp, n = self._import_stream(
                        e.payload, rid, prompt,
                        exclude | {rep.name}, attempt_timeout)
                if resp is not None:
                    self._m_migrations.inc()
                    self.log.append(
                        ("migrate", rid, rep.name, dest.name,
                         resp.get("migrated_blocks")))
                    self.tracer.instant(
                        "route.migrated", cat="router", req=rid,
                        source=rep.name, dest=dest.name,
                        blocks=resp.get("migrated_blocks"))
                    # the import's response carries the stream's FULL
                    # token history: splice the unseen tail into the
                    # live stream (indices already forwarded before
                    # the migration are suppressed by _deliver)
                    _deliver(emitted
                             + [int(x) for x in
                                resp.get("generated", [])], 0)
                    return self._serve(rid, prompt, emitted,
                                       resp.get("generated", []),
                                       dest, attempt + n, t0, resp)
                # nobody took the payload; the source stream is
                # already terminated, so salvage what it had emitted
                # and fail over like a disconnect (greedy resumes,
                # seeded restarts — token-identical either way)
                if greedy and e.emitted:
                    emitted.extend(e.emitted)
                self.log.append(("failover", rid, rep.name,
                                 "migrate_lost"))
                self._m_retries.inc()
                exclude.add(rep.name)
                attempt += 1
                continue
            except Exception as e:
                last_exc = e
                kind, retryable, hint, got = self._classify(
                    e, idempotent)
                replica_died = kind in ("abandoned", "disconnect",
                                        "refused", "timeout")
                if got:
                    if greedy:
                        # greedy failover RESUMES: prompt + emitted is
                        # the next attempt's context, the continuation
                        # is token-identical to the uninterrupted run
                        emitted.extend(int(t) for t in got)
                    # sampled streams restart from scratch instead: a
                    # seeded re-run from token 0 is identical, while
                    # resuming mid-stream would shift the device
                    # sampling counter and fork the stream
                self.log.append(("retry" if not replica_died
                                 else "failover", rid, rep.name, kind))
                self.tracer.instant("route.failover" if replica_died
                                    else "route.retry", cat="router",
                                    req=rid, replica=rep.name,
                                    kind=kind, attempt=attempt)
                if replica_died:
                    self._m_failovers.inc()
                if not retryable or attempt >= self.policy.retry_max:
                    self._m_failed.inc()
                    raise RequestFailed(
                        f"request {rid} failed on {rep.name} after "
                        f"{attempt + 1} attempt(s): [{kind}] {e}",
                        cause=e) from e
                self._m_retries.inc()
                exclude.add(rep.name)
                # dead-replica failovers skip the backoff (the work is
                # fine, the host is not); transient failures back off
                # exponentially with seeded jitter, honoring a
                # replica's own Retry-After when it is larger
                if not replica_died or hint is not None:
                    wait = self._backoff(rid, attempt, hint)
                    if deadline is not None:
                        wait = min(wait,
                                   max(deadline - time.monotonic(),
                                       0.0))
                    with self.tracer.span(
                            "route.retry", cat="router", req=rid,
                            attempt=attempt, kind=kind,
                            backoff_ms=round(wait * 1e3, 3)):
                        time.sleep(wait)
                attempt += 1
                continue
            # a fired hedge was a real second dispatch: "attempts"
            # counts dispatches, whichever slot won
            _deliver(emitted
                     + [int(x) for x in resp.get("generated", [])], 0)
            return self._serve(rid, prompt, emitted,
                               resp.get("generated", []),
                               served_by, attempt + (1 if hedged
                                                     else 0),
                               t0, resp)

    def _serve(self, rid, prompt, emitted, new_tokens, rep, attempts,
               t0, resp=None):
        generated = [int(t) for t in emitted] \
            + [int(t) for t in new_tokens]
        ms = (time.monotonic() - t0) * 1e3
        self._m_lat.observe(ms)
        self._m_served.inc()
        name = rep.name if rep is not None else None
        self.log.append(("serve", rid, name, attempts))
        self.tracer.instant("route.served", cat="router", req=rid,
                            replica=name, attempts=attempts,
                            ms=round(ms, 3))
        out = {
            "req": rid, "replica": name, "attempts": attempts + 1,
            "ids": prompt + generated, "generated": generated,
        }
        if resp:
            for k in ("ttft_ms", "id"):
                if k in resp:
                    out[f"replica_{k}" if k == "id" else k] = resp[k]
        return out

    # -- observability -------------------------------------------------
    def route_log(self):
        """Snapshot of the structured routing log (bounded ring)."""
        return list(self.log)

    def chrome_trace(self):
        return self.tracer.chrome_trace(process_name="router")


class InProcessReplica:
    """Replica transport wrapping a LOCAL ``Engine`` — the tier-1
    fake-network layer: tests, benches, the example fleet, and
    single-host multi-replica serving all use it, and the ``net_*``
    fault sites of ``serving.faults`` thread through it with a
    deterministic per-replica OPERATION counter as the schedule tick
    (op 0, 1, 2, ... in submission order — wall-clock free, so a
    seeded storm replays exactly).

    Mid-body disconnects are deterministic: when ``net_disconnect`` is
    scheduled for an op, the engine is allowed to finish the stream
    and the transport then "delivers" only the first
    ``disconnect_after`` tokens before raising — exactly what a
    client sees when the peer dies mid-response, with a schedule-
    stable emitted count (the orphaned tail is discarded, never
    double-served).

    ``kill()`` flips a hard-down switch (every op and probe refuses,
    like a dead process) — the example's replica-kill demo;
    ``revive()`` brings it back.
    """

    def __init__(self, name, engine, faults=None,
                 disconnect_after=2, poll_s=0.002, role="mixed"):
        if role not in ("mixed", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        self.name = str(name)
        self.engine = engine
        self.faults = faults
        self.disconnect_after = int(disconnect_after)
        self.poll_s = float(poll_s)
        self.role = role     # advertised in probes; the router's
        #   disaggregated pick() is what makes it binding
        self.killed = False
        self.incarnation = 0  # supervisor restart generation: bumped
        #   by revive(bump_incarnation=True) to model a supervised
        #   respawn on the same address
        self._ops = itertools.count()
        self._probe_ops = itertools.count()
        self.served = []     # router-delivered op ids (test surface)

    # the router's /replicas view shows where the replica lives
    address = "in-process"

    def kill(self):
        self.killed = True

    def revive(self, bump_incarnation=False):
        """Bring a killed replica back.  Default models the SAME
        process answering again (probe-driven breaker recovery walks
        OPEN -> HALF_OPEN -> trial); ``bump_incarnation=True`` models
        a supervised RESPAWN — a new process on the old address whose
        first probe makes the router reset breaker + health history
        instead of trialing through half-open."""
        self.killed = False
        if bump_incarnation:
            self.incarnation += 1

    def _maybe(self, site, tick, **kw):
        if self.faults is not None \
                and self.faults.scheduled(site, tick):
            self.faults.fire(site, tick, **kw)

    def probe(self):
        t = next(self._probe_ops)
        if self.killed:
            raise NetRefused(
                f"replica {self.name} is down (probe {t})")
        self._maybe("net_refuse", t)
        self._maybe("net_blackhole", t)
        self._maybe("net_slow", t)
        eng = self.engine
        paged = getattr(eng, "_paged", False)
        rate = getattr(eng, "drain_rate", lambda: None)()
        return {
            "status": "ok",
            "queue_depth": eng.queue.depth(),
            "slots_total": eng.num_slots,
            "slots_free": eng.scheduler.free_count(),
            "kv_blocks_free": (eng.block_pool.free_count()
                               if paged else None),
            "kv_block_size": (eng._bs if paged else None),
            "mesh_shape": getattr(eng, "mesh_axes", None),
            "mp": getattr(eng, "mp", 1),
            "dp": getattr(eng, "dp", 1),
            "weight_dtype": getattr(eng, "_weight_dtype_str", None),
            "kv_dtype": getattr(eng, "_kv_dtype_str", None),
            "kv_block_bytes": getattr(eng, "_kv_code_bytes_per_shard",
                                      None),
            "kv_scale_bytes": getattr(
                eng, "_kv_scale_bytes_per_shard", None),
            "drain_rate_tps": rate,
            "draining": bool(getattr(eng, "_draining", False)),
            "watchdog_fired": bool(getattr(eng, "_watchdog_fired",
                                           False)),
            "role": self.role,
            "incarnation": self.incarnation,
            "adapters": (eng.adapters.names()
                         if getattr(eng, "adapters", None) is not None
                         else []),
            "streams_active": (eng.streams_active()
                               if hasattr(eng, "streams_active")
                               else 0),
            "attn_impl": getattr(eng, "attn_impl", "xla"),
            "max_context_len": getattr(eng, "_max_context_len", 0),
        } | (
            # host-RAM offload tier signals, matching /healthz: only
            # advertised when the tier exists (probers key off
            # presence)
            {"kv_host_blocks": len(eng.host_store),
             "kv_host_bytes": eng.host_store.bytes_used,
             "kv_host_capacity_mb": eng.host_store.capacity_mb,
             "offload_hit_tokens_total": int(
                 eng._m_offload_hit_tokens.value)}
            if getattr(eng, "host_store", None) is not None else {})

    def generate(self, payload, should_abort=None, on_token=None):
        t = next(self._ops)
        if self.killed:
            raise NetRefused(f"replica {self.name} is down (op {t})")
        self._maybe("net_refuse", t)
        self._maybe("net_blackhole", t, abort=should_abort)
        self._maybe("net_slow", t)
        disconnect = (self.faults is not None
                      and self.faults.scheduled("net_disconnect", t))
        try:
            req = self.engine.submit(
                payload["prompt"],
                max_new_tokens=payload.get("max_new_tokens", 16),
                eos_token_id=payload.get("eos_token_id"),
                temperature=payload.get("temperature", 1.0),
                top_k=payload.get("top_k", 0),
                top_p=payload.get("top_p", 1.0),
                seed=payload.get("seed"),
                priority=payload.get("priority", 0),
                tenant=payload.get("tenant"),
                adapter=payload.get("adapter"))
        except UnknownAdapter as e:
            # same machine-readable 404 as httpd: the adapter was
            # unloaded between the router's probe and this dispatch —
            # the caller's model name is wrong HERE, not a failure
            raise ReplicaHTTPError(
                f"replica {self.name} rejected the request: {e}",
                404, reason="unknown_adapter") from e
        except Rejected as e:
            raise ReplicaUnavailable(
                str(e), status=503,
                retry_after=getattr(e, "retry_after", None),
                reason=type(e).__name__) from e
        except (TypeError, ValueError) as e:
            # the engine REJECTED the arguments — the caller's fault,
            # exactly what httpd surfaces as a 400: map it the same so
            # a bad client cannot poison this replica's breaker (the
            # router treats 4xx as a health signal, not a failure)
            raise ReplicaHTTPError(
                f"replica {self.name} rejected the request: {e}",
                400, reason="bad_request") from e
        budget = payload.get("timeout_s")
        if on_token is not None:
            return self._stream_generate(req, payload, t, budget,
                                         should_abort, disconnect,
                                         on_token)
        deadline = (None if budget is None
                    else time.monotonic() + float(budget))
        while not req.done():
            if should_abort is not None and should_abort():
                if req.first_token_at is None:
                    # queued-but-unstarted on a dying replica: clean
                    # failover, nothing emitted, nothing lost
                    raise ReplicaAbandoned(
                        f"replica {self.name} abandoned queued "
                        f"request (op {t})")
                raise NetDisconnect(
                    f"replica {self.name} died mid-stream (op {t})",
                    emitted=list(req.generated))
            if deadline is not None and time.monotonic() > deadline:
                raise NetTimeout(
                    f"replica {self.name} exceeded the "
                    f"{budget}s attempt budget (op {t})")
            req._done.wait(self.poll_s)
        if req.error is not None:
            from .engine import Migrated  # lazy: HTTP-only routers
            #   never import the (jax-heavy) engine module
            if isinstance(req.error, Migrated):
                # the stream was MIGRATED out from under this waiter
                # (a rebalance): hand the payload up — the router
                # re-lands it and the same logical request continues
                raise StreamMigrated(
                    f"replica {self.name} migrated the stream out "
                    f"(op {t})", payload=req.error.payload,
                    emitted=req.error.emitted)
            # an engine-side death mid-request IS the failover case:
            # deliver the salvageable prefix as a disconnect
            raise NetDisconnect(
                f"replica {self.name} failed the request: "
                f"{req.error} (op {t})", emitted=list(req.generated))
        gen = [int(x) for x in req.generated]
        if disconnect:
            k = min(self.disconnect_after, len(gen))
            self.faults.fire("net_disconnect", t, emitted=gen[:k])
        self.served.append(t)
        ttft = None
        if req.first_token_at is not None:
            ttft = round((req.first_token_at - req.submitted_at)
                         * 1e3, 3)
        return {
            "id": req.id,
            "ids": [int(x) for x in payload["prompt"]] + gen,
            "generated": gen, "ttft_ms": ttft,
        }

    def _stream_generate(self, req, payload, t, budget, should_abort,
                         disconnect, on_token):
        """The live half of ``generate``: attach a ``TokenStream`` to
        the submitted request and forward every token through
        ``on_token`` the moment the engine emits it.  A scheduled
        ``net_disconnect`` cuts the stream after ``disconnect_after``
        FORWARDED tokens (the client's view of a peer dying mid-SSE);
        every failure carries ``emitted`` = exactly the tokens this
        transport forwarded, so the router's splice resumes without
        a gap or a duplicate."""
        stream = TokenStream(req, heartbeat_s=self.poll_s)
        deadline = (None if budget is None
                    else time.monotonic() + float(budget))
        sent = []
        limit = self.disconnect_after if disconnect else None
        for ev in stream:
            if ev.kind == "token":
                if limit is not None and len(sent) >= limit:
                    # the scheduled mid-stream client death: the cut
                    # tail is orphaned on the replica, never delivered
                    self.faults.fire("net_disconnect", t,
                                     emitted=list(sent))
                on_token(int(ev.token))
                sent.append(int(ev.token))
                continue
            if ev.kind == "heartbeat":
                if should_abort is not None and should_abort():
                    if not sent and req.first_token_at is None:
                        raise ReplicaAbandoned(
                            f"replica {self.name} abandoned queued "
                            f"request (op {t})")
                    raise NetDisconnect(
                        f"replica {self.name} died mid-stream "
                        f"(op {t})", emitted=list(sent))
                if deadline is not None \
                        and time.monotonic() > deadline:
                    raise NetTimeout(
                        f"replica {self.name} exceeded the "
                        f"{budget}s attempt budget (op {t})")
                continue
            break                      # terminal done / error
        if stream.error is not None:
            from .engine import Migrated  # lazy: HTTP-only routers
            #   never import the (jax-heavy) engine module
            if isinstance(stream.error, Migrated):
                raise StreamMigrated(
                    f"replica {self.name} migrated the stream out "
                    f"(op {t})", payload=stream.error.payload,
                    emitted=stream.error.emitted)
            raise NetDisconnect(
                f"replica {self.name} failed the request: "
                f"{stream.error} (op {t})", emitted=list(sent))
        self.served.append(t)
        ttft = None
        if req.first_token_at is not None:
            ttft = round((req.first_token_at - req.submitted_at)
                         * 1e3, 3)
        return {
            "id": req.id,
            "ids": [int(x) for x in payload["prompt"]] + sent,
            "generated": sent, "ttft_ms": ttft,
            "streamed": len(sent),
        }

    def _wait_out(self, req, t, budget, should_abort):
        """Block until ``req`` completes (the shared tail of generate
        / migrate flows): abort, per-attempt budget, and engine-side
        error mapping all behave exactly like ``generate()``."""
        deadline = (None if budget is None
                    else time.monotonic() + float(budget))
        while not req.done():
            if should_abort is not None and should_abort():
                raise NetDisconnect(
                    f"replica {self.name} died mid-stream (op {t})",
                    emitted=list(req.generated))
            if deadline is not None and time.monotonic() > deadline:
                raise NetTimeout(
                    f"replica {self.name} exceeded the "
                    f"{budget}s attempt budget (op {t})")
            req._done.wait(self.poll_s)
        if req.error is not None:
            raise NetDisconnect(
                f"replica {self.name} failed the request: "
                f"{req.error} (op {t})", emitted=list(req.generated))
        return [int(x) for x in req.generated]

    def migrate_export(self, body, should_abort=None):
        """KV block export (the in-process `/migrate/export`).  Three
        shapes: ``prefix_only`` exports the trie's cached blocks for
        a token span; ``deliver=error`` preempts a live stream and
        hands the payload to its own waiter (the rebalance path —
        this transport returns no payload); otherwise submit-then-
        export: run the prompt to ``min_tokens`` and export the warm
        stream (the disaggregated prefill leg).  The ``migrate_wire``
        fault site fires AFTER a successful export, on this replica's
        operation counter — the payload vanishes in flight with the
        source stream already terminated, the worst-case loss the
        chaos tests replay."""
        t = next(self._ops)
        if self.killed:
            raise NetRefused(f"replica {self.name} is down (op {t})")
        self._maybe("net_refuse", t)
        self._maybe("net_blackhole", t, abort=should_abort)
        self._maybe("net_slow", t)
        eng = self.engine
        budget = body.get("timeout_s")
        timeout = 30.0 if budget is None else float(budget)
        if body.get("prefix_only"):
            try:
                payload = eng.export_prefix(body.get("tokens") or [],
                                            timeout=timeout)
            except Exception as e:
                raise ReplicaUnavailable(
                    f"replica {self.name} declined the prefix "
                    f"export: {e} (op {t})",
                    reason="migrate_declined") from e
            self._maybe("migrate_wire", t, emitted=[])
            return {"completed": False, "generated": [],
                    "payload": payload}
        if body.get("deliver") == "error":
            # rebalance: the payload rides the victim's Migrated
            # error to its waiter, never over this return path
            try:
                res = eng.migrate_out(
                    request_id=body.get("request_id"),
                    min_tokens=int(body.get("min_tokens", 1)),
                    deliver="error", timeout=timeout)
            except KeyError as e:
                raise ReplicaHTTPError(
                    f"replica {self.name}: {e} (op {t})", 404,
                    reason="not_found") from e
            except TimeoutError as e:
                raise NetTimeout(
                    f"replica {self.name} export timed out "
                    f"(op {t})") from e
            except Exception as e:
                raise ReplicaUnavailable(
                    f"replica {self.name} declined the export: {e} "
                    f"(op {t})", reason="migrate_declined") from e
            return {"completed": bool(res["completed"]),
                    "generated": [int(x) for x in res["generated"]],
                    "payload": None}
        req = None
        if body.get("request_id") is None:
            try:
                req = eng.submit(
                    body["prompt"],
                    max_new_tokens=body.get("max_new_tokens", 16),
                    eos_token_id=body.get("eos_token_id"),
                    temperature=body.get("temperature", 1.0),
                    top_k=body.get("top_k", 0),
                    top_p=body.get("top_p", 1.0),
                    seed=body.get("seed"),
                    priority=body.get("priority", 0),
                    tenant=body.get("tenant"))
            except Rejected as e:
                raise ReplicaUnavailable(
                    str(e), status=503,
                    retry_after=getattr(e, "retry_after", None),
                    reason=type(e).__name__) from e
            except (TypeError, ValueError) as e:
                raise ReplicaHTTPError(
                    f"replica {self.name} rejected the request: {e}",
                    400, reason="bad_request") from e
            rid = req.id
        else:
            rid = body["request_id"]
        try:
            res = eng.migrate_out(
                request_id=rid,
                min_tokens=int(body.get("min_tokens", 1)),
                deliver="return", timeout=timeout)
        except KeyError as e:
            raise ReplicaHTTPError(
                f"replica {self.name} has no request {rid!r} "
                f"(op {t})", 404, reason="not_found") from e
        except TimeoutError as e:
            raise NetTimeout(
                f"replica {self.name} export timed out (op {t})") \
                from e
        except Exception as e:
            if req is None:
                raise ReplicaUnavailable(
                    f"replica {self.name} declined the export: {e} "
                    f"(op {t})", reason="migrate_declined") from e
            # the engine declined the export of OUR OWN submission
            # (e.g. an injected migrate_export fault): the stream
            # stays on the source — serve it whole right here
            gen = self._wait_out(req, t, budget, should_abort)
            self.served.append(t)
            return {"completed": True, "generated": gen,
                    "payload": None}
        gen = [int(x) for x in res["generated"]]
        # the wire crossing: the source stream is ALREADY terminated
        # when this fires, so the payload is genuinely lost in flight
        self._maybe("migrate_wire", t, emitted=gen)
        if res["completed"]:
            self.served.append(t)
        return {"completed": bool(res["completed"]),
                "generated": gen, "payload": res["payload"]}

    def migrate_import(self, body, should_abort=None):
        """KV block import (the in-process `/migrate/import`): adopt
        the payload's blocks, resume the stream, and block until it
        completes — the response is ``generate()``-shaped plus
        ``migrated_blocks``.  A body with no ``request`` is a prefix
        warm (adopt into the trie, nothing to resume).  The
        ``migrate_wire`` site here fires BEFORE the engine sees the
        payload: the caller still holds it and re-imports elsewhere."""
        t = next(self._ops)
        if self.killed:
            raise NetRefused(f"replica {self.name} is down (op {t})")
        self._maybe("net_refuse", t)
        self._maybe("net_blackhole", t, abort=should_abort)
        self._maybe("net_slow", t)
        self._maybe("migrate_wire", t)
        eng = self.engine
        budget = body.get("timeout_s")
        timeout = 30.0 if budget is None else float(budget)
        if body.get("request") is None:
            try:
                res = eng.import_prefix(body, timeout=timeout)
            except Exception as e:
                raise ReplicaUnavailable(
                    f"replica {self.name} declined the prefix "
                    f"import: {e} (op {t})",
                    reason="migrate_failed") from e
            return dict(res)
        try:
            res = eng.migrate_in(body, timeout=timeout)
        except Rejected as e:
            raise ReplicaUnavailable(
                str(e), status=503,
                retry_after=getattr(e, "retry_after", None),
                reason=type(e).__name__) from e
        except KVDtypeMismatch as e:
            # same machine-readable reason as httpd's 400: the
            # pairing is wrong, not the payload — the router's
            # pre-filter keys off this via the probed kv_dtype
            raise ReplicaHTTPError(
                f"replica {self.name} rejected the payload: {e} "
                f"(op {t})", 400, reason="kv_dtype_mismatch") from e
        except (TypeError, ValueError) as e:
            # a geometry/shape mismatch is NON-retryable against any
            # identically-configured replica — surface it as a 400
            raise ReplicaHTTPError(
                f"replica {self.name} rejected the payload: {e} "
                f"(op {t})", 400, reason="bad_request") from e
        except TimeoutError as e:
            raise NetTimeout(
                f"replica {self.name} import timed out (op {t})") \
                from e
        except Exception as e:
            # injected migrate_import fault and friends: the engine
            # ADOPTED NOTHING (blocks rolled back to refcount 0), so
            # the caller's payload is safe to retry elsewhere
            raise ReplicaUnavailable(
                f"replica {self.name} failed the import: {e} "
                f"(op {t})", reason="migrate_failed") from e
        req = res["request"]
        gen = self._wait_out(req, t, budget, should_abort)
        self.served.append(t)
        ttft = None
        if req.first_token_at is not None:
            ttft = round((req.first_token_at - req.submitted_at)
                         * 1e3, 3)
        rq = body.get("request") or {}
        prompt = [int(x) for x in rq.get("prompt") or []]
        return {
            "id": req.id, "ids": prompt + gen, "generated": gen,
            "ttft_ms": ttft, "migrated_blocks": res["blocks"],
        }


class HttpReplicaClient:
    """Replica transport over HTTP (``serving.httpd`` endpoints):
    ``probe()`` = GET /healthz, ``generate()`` = POST /generate.
    Failure mapping mirrors the injected ``net_*`` vocabulary so the
    router's classifier has ONE decision table: connection refused ->
    NetRefused, socket timeout -> NetTimeout, truncated body ->
    NetDisconnect (no emitted context — the whole-completion API
    cannot say how far it got), 503/429 -> ReplicaUnavailable with
    the Retry-After header honored, other HTTP errors ->
    ReplicaHTTPError carrying the machine-readable ``reason`` the
    error body now always includes.

    ``should_abort`` cannot interrupt a blocking socket read; a dead
    replica surfaces as NetTimeout after ``timeout_s`` instead (the
    in-process transport is the one that abandons instantly)."""

    def __init__(self, address, probe_timeout_s=2.0, timeout_s=60.0):
        self.address = address.rstrip("/")
        self.probe_timeout_s = float(probe_timeout_s)
        self.timeout_s = float(timeout_s)

    def _error_body(self, e):
        try:
            import json
            return json.loads(e.read())
        except Exception:
            return {}

    @staticmethod
    def _retry_after_s(ra):
        """A Retry-After header is delta-seconds OR an HTTP-date (RFC
        7231 — proxies in front of a replica emit the date form);
        unparseable values degrade to None, never to a crash in the
        error handler."""
        if not ra:
            return None
        try:
            return float(ra)
        except ValueError:
            pass
        try:
            import datetime
            from email.utils import parsedate_to_datetime
            dt = parsedate_to_datetime(ra)
            now = datetime.datetime.now(dt.tzinfo)
            return max((dt - now).total_seconds(), 0.0)
        except Exception:
            return None

    def probe(self):
        import json
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(
                    self.address + "/healthz",
                    timeout=self.probe_timeout_s) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            body = self._error_body(e)
            raise ReplicaHTTPError(
                f"probe {self.address}: HTTP {e.code}", e.code,
                reason=body.get("reason")) from e
        except Exception as e:
            raise self._map_net(e, "probe") from e

    def _map_net(self, e, what):
        import socket
        import urllib.error
        if isinstance(e, urllib.error.URLError):
            reason = getattr(e, "reason", None)
            if isinstance(reason, ConnectionRefusedError) \
                    or isinstance(reason, OSError) \
                    and getattr(reason, "errno", None) in (111, 61):
                return NetRefused(
                    f"{what} {self.address}: connection refused")
            if isinstance(reason, socket.timeout):
                return NetTimeout(
                    f"{what} {self.address}: timed out")
            if isinstance(reason, (ConnectionResetError,
                                   ConnectionError)) \
                    or isinstance(reason, OSError) \
                    and getattr(reason, "errno", None) == 104:
                # connect-phase reset (replica died mid-handshake):
                # retryable like any other transport death
                return NetDisconnect(
                    f"{what} {self.address}: connection reset")
        if isinstance(e, socket.timeout) \
                or isinstance(e, TimeoutError):
            return NetTimeout(f"{what} {self.address}: timed out")
        if isinstance(e, (ConnectionResetError, ConnectionError)):
            return NetDisconnect(
                f"{what} {self.address}: connection reset")
        return e

    def _post(self, path, payload, what=None):
        """POST one JSON body and map every transport failure into
        the router's classified vocabulary (the shared tail of
        ``generate`` / ``migrate_export`` / ``migrate_import``)."""
        import http.client
        import json
        import urllib.error
        import urllib.request
        what = what or path.strip("/")
        body = {k: v for k, v in payload.items() if k != "timeout_s"}
        timeout = float(payload.get("timeout_s") or self.timeout_s)
        data = json.dumps(body).encode()
        req = urllib.request.Request(
            self.address + path, data=data,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            bodyj = self._error_body(e)
            ra = e.headers.get("Retry-After")
            if e.code in (503, 429):
                raise ReplicaUnavailable(
                    bodyj.get("error", f"HTTP {e.code}"),
                    status=e.code,
                    retry_after=self._retry_after_s(ra),
                    reason=bodyj.get("reason")) from e
            raise ReplicaHTTPError(
                bodyj.get("error", f"HTTP {e.code}"), e.code,
                reason=bodyj.get("reason")) from e
        except http.client.IncompleteRead as e:
            raise NetDisconnect(
                f"{what} {self.address}: response truncated "
                "mid-body") from e
        except (json.JSONDecodeError, ValueError) as e:
            raise NetDisconnect(
                f"{what} {self.address}: unparseable partial "
                f"response ({e})") from e
        except Exception as e:
            raise self._map_net(e, what) from e

    def generate(self, payload, should_abort=None, on_token=None):
        if on_token is None:
            return self._post("/generate", payload)
        return self._stream_generate(payload, on_token)

    def _stream_generate(self, payload, on_token):
        """POST /generate ``{"stream": true}`` and follow the
        replica's SSE frames (the client half of httpd's
        ``_stream_response``): every ``token`` frame fires
        ``on_token`` immediately, ``done`` returns its /generate-
        shaped payload, a terminal ``error`` frame maps into the
        classified vocabulary (shed -> ReplicaUnavailable with its
        retry_after, result_timeout -> NetTimeout, replica-side death
        -> NetDisconnect carrying exactly the tokens this socket
        delivered, so a greedy failover resumes without a gap)."""
        import http.client
        import json
        import urllib.error
        import urllib.request
        body = {k: v for k, v in payload.items() if k != "timeout_s"}
        body["stream"] = True
        timeout = float(payload.get("timeout_s") or self.timeout_s)
        req = urllib.request.Request(
            self.address + "/generate",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        sent = []
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                for event, dstr in parse_sse(resp):
                    try:
                        d = json.loads(dstr)
                    except ValueError:
                        continue
                    if event == "token":
                        tok = int(d["token"])
                        on_token(tok)
                        sent.append(tok)
                    elif event == "done":
                        return d
                    elif event == "error":
                        reason = d.get("reason")
                        msg = (f"generate {self.address}: terminal "
                               f"stream error [{reason}] "
                               f"{d.get('error')}")
                        if reason == "result_timeout":
                            raise NetTimeout(msg)
                        if reason in ("internal", "drain_failed",
                                      None):
                            raise NetDisconnect(
                                msg, emitted=list(sent))
                        raise ReplicaUnavailable(
                            msg, status=503,
                            retry_after=d.get("retry_after"),
                            reason=reason)
                raise NetDisconnect(
                    f"generate {self.address}: stream ended without "
                    "a terminal event", emitted=list(sent))
        except (NetTimeout, NetDisconnect, ReplicaUnavailable):
            raise
        except urllib.error.HTTPError as e:
            # pre-stream rejection: shed (503/429, Retry-After
            # honored), unknown_adapter (404), bad_request (400)
            bodyj = self._error_body(e)
            ra = e.headers.get("Retry-After")
            if e.code in (503, 429):
                raise ReplicaUnavailable(
                    bodyj.get("error", f"HTTP {e.code}"),
                    status=e.code,
                    retry_after=self._retry_after_s(ra),
                    reason=bodyj.get("reason")) from e
            raise ReplicaHTTPError(
                bodyj.get("error", f"HTTP {e.code}"), e.code,
                reason=bodyj.get("reason")) from e
        except http.client.IncompleteRead as e:
            raise NetDisconnect(
                f"generate {self.address}: stream truncated "
                "mid-frame", emitted=list(sent)) from e
        except Exception as e:
            mapped = self._map_net(e, "generate")
            if isinstance(mapped, NetDisconnect):
                # re-raise with the delivered-token context a
                # mid-stream reset salvages
                raise NetDisconnect(str(mapped),
                                    emitted=list(sent)) from e
            if mapped is e:
                raise
            raise mapped from e

    def migrate_export(self, payload, should_abort=None):
        """POST /migrate/export — the returned ``payload`` (when one
        exists) is wire-form (``data_b64``), which the importing
        engine decodes itself; it round-trips straight into
        ``migrate_import`` unchanged."""
        return self._post("/migrate/export", payload,
                          what="migrate_export")

    def migrate_import(self, payload, should_abort=None):
        return self._post("/migrate/import", payload,
                          what="migrate_import")
